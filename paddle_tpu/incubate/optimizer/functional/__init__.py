"""Functional optimizers: BFGS / L-BFGS minimizers.

Reference analog: python/paddle/incubate/optimizer/functional/{bfgs.py:27,
lbfgs.py} — quasi-Newton minimization with strong-Wolfe line search.
TPU-first: the whole solve is jax (grad via jax.grad, updates jittable);
the objective is wrapped so paddle Tensors cross the boundary.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ....framework.core import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _as_jax_objective(objective_func):
    def f(x):
        out = objective_func(Tensor(x, stop_gradient=True))
        return jnp.reshape(out._value if isinstance(out, Tensor)
                           else jnp.asarray(out), ())
    return f


def _line_search(f, g, x, d, fx, gx, initial_step=1.0, max_iters=50,
                 c1=1e-4, c2=0.9):
    """Backtracking line search with a curvature-driven halving pass (the
    reference's strong_wolfe role). Returns (step, calls, fx_new, gx_new)
    so the caller reuses the already-computed objective/gradient at the
    accepted point — no wasted gradient evaluation."""
    a = initial_step
    calls = 0
    dg0 = float(gx @ d)
    best = None
    for _ in range(max_iters):
        x_new = x + a * d
        fx_new = f(x_new)
        calls += 1
        if float(fx_new) <= float(fx) + c1 * a * dg0:   # Armijo holds
            g_new = g(x_new)
            if abs(float(g_new @ d)) <= c2 * abs(dg0):  # curvature holds
                return a, calls, fx_new, g_new
            if best is None:
                best = (a, fx_new, g_new)   # acceptable fallback
        a *= 0.5
    if best is not None:
        a, fx_new, g_new = best
        return a, calls, fx_new, g_new
    x_new = x + a * d
    return a, calls, f(x_new), g(x_new)


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    """Full-memory BFGS (reference bfgs.py:27, Nocedal & Wright Alg 6.1).
    Returns (is_converge, num_func_calls, position, objective_value,
    objective_gradient, inverse_hessian_estimate)."""
    f = _as_jax_objective(objective_func)
    g = jax.grad(f)
    x = jnp.asarray(initial_position._value
                    if isinstance(initial_position, Tensor)
                    else initial_position, dtype).reshape(-1)
    n = x.shape[0]
    H = jnp.eye(n, dtype=x.dtype) if initial_inverse_hessian_estimate is None \
        else jnp.asarray(initial_inverse_hessian_estimate._value
                         if isinstance(initial_inverse_hessian_estimate,
                                       Tensor)
                         else initial_inverse_hessian_estimate, dtype)
    fx = f(x)
    gx = g(x)
    calls = 1
    converged = False
    for _ in range(max_iters):
        if float(jnp.abs(gx).max()) <= tolerance_grad:
            converged = True
            break
        d = -(H @ gx)
        a, ls_calls, fx_new, g_new = _line_search(
            f, g, x, d, fx, gx, initial_step=initial_step_length,
            max_iters=max_line_search_iters)
        calls += ls_calls
        x_new = x + a * d
        s = x_new - x
        y = g_new - gx
        sy = float(s @ y)
        if abs(float(jnp.abs(s).max())) <= tolerance_change:
            x, gx, fx = x_new, g_new, fx_new
            converged = True
            break
        if sy > 1e-10:
            rho = 1.0 / sy
            I = jnp.eye(n, dtype=x.dtype)
            V = I - rho * jnp.outer(s, y)
            H = V @ H @ V.T + rho * jnp.outer(s, s)
        x, gx, fx = x_new, g_new, fx_new
    return (converged, calls, Tensor(x), Tensor(fx), Tensor(gx), Tensor(H))


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7,
                   tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    """Limited-memory BFGS via the two-loop recursion (reference lbfgs.py).
    Returns (is_converge, num_func_calls, position, objective_value,
    objective_gradient) — no dense inverse Hessian, by definition."""
    f = _as_jax_objective(objective_func)
    g = jax.grad(f)
    x = jnp.asarray(initial_position._value
                    if isinstance(initial_position, Tensor)
                    else initial_position, dtype).reshape(-1)
    fx = f(x)
    gx = g(x)
    calls = 1
    s_hist, y_hist = [], []
    converged = False
    for _ in range(max_iters):
        if float(jnp.abs(gx).max()) <= tolerance_grad:
            converged = True
            break
        # two-loop recursion
        q = gx
        alphas = []
        for s, y in reversed(list(zip(s_hist, y_hist))):
            rho = 1.0 / float(s @ y)
            alpha = rho * float(s @ q)
            q = q - alpha * y
            alphas.append((alpha, rho))
        if s_hist:
            s, y = s_hist[-1], y_hist[-1]
            gamma = float(s @ y) / float(y @ y)
            q = gamma * q
        for (alpha, rho), (s, y) in zip(reversed(alphas),
                                        zip(s_hist, y_hist)):
            beta = rho * float(y @ q)
            q = q + (alpha - beta) * s
        d = -q
        a, ls_calls, fx_new, g_new = _line_search(
            f, g, x, d, fx, gx, initial_step=initial_step_length,
            max_iters=max_line_search_iters)
        calls += ls_calls
        x_new = x + a * d
        s = x_new - x
        y = g_new - gx
        if abs(float(jnp.abs(s).max())) <= tolerance_change:
            x, gx, fx = x_new, g_new, fx_new
            converged = True
            break
        if float(s @ y) > 1e-10:
            s_hist.append(s)
            y_hist.append(y)
            if len(s_hist) > history_size:
                s_hist.pop(0)
                y_hist.pop(0)
        x, gx, fx = x_new, g_new, fx_new
    return (converged, calls, Tensor(x), Tensor(fx), Tensor(gx))
