"""GPT model family (decoder-only transformer LM).

Reference analog: the Fleet GPT-3 training path the reference was built for
(SURVEY.md north star; mp layers fleet/layers/mpu/mp_layers.py + fused
transformer ops fluid/operators/fused/). Model configs follow the standard
GPT-2 124M / GPT-3 1.3B / 6.7B shapes from BASELINE.md.

TPU-first design:
  - attention core routes through F.scaled_dot_product_attention → Pallas
    flash kernel when eligible (bf16, block-aligned seq);
  - hybrid parallelism is expressed as NamedShardings over the global mesh
    (`shard_gpt`): embedding/vocab and qkv/ffn columns on the "model" axis,
    activations on "data" (+ sequence on "sep" when present) — XLA inserts the
    Megatron collectives;
  - everything trains through one jitted step (paddle_tpu.jit.TrainStep or
    the sharded variant in __graft_entry__).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

from ...nn.layer_base import Layer
from ...nn.layer.container import LayerList
from ...nn.layer.common import Linear, Embedding, Dropout
from ...nn.layer.norm import LayerNorm
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.initializer_util import ParamAttr
from ...ops import manipulation as manip
from ...framework.core import Tensor

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "GPTPretrainingCriterion",
           "gpt2_124m", "gpt2_355m", "gpt3_1p3b", "gpt3_6p7b", "shard_gpt",
           "GPTEmbeddingPipe", "GPTHeadPipe", "gpt_pipeline_layers",
           "GPTDecodeStep"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304            # padded to a multiple of 128 for MXU
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    use_flash_attention: bool = True
    tie_word_embeddings: bool = True


def gpt2_124m(**overrides):
    return GPTConfig(**{**dict(hidden_size=768, num_hidden_layers=12,
                               num_attention_heads=12, intermediate_size=3072),
                        **overrides})


def gpt2_355m(**overrides):
    return GPTConfig(**{**dict(hidden_size=1024, num_hidden_layers=24,
                               num_attention_heads=16, intermediate_size=4096),
                        **overrides})


def gpt3_1p3b(**overrides):
    return GPTConfig(**{**dict(hidden_size=2048, num_hidden_layers=24,
                               num_attention_heads=16, intermediate_size=8192,
                               max_position_embeddings=2048),
                        **overrides})


def gpt3_6p7b(**overrides):
    return GPTConfig(**{**dict(hidden_size=4096, num_hidden_layers=32,
                               num_attention_heads=32, intermediate_size=16384,
                               max_position_embeddings=2048),
                        **overrides})


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        self.hidden_size = config.hidden_size
        init = I.Normal(0.0, config.initializer_range)
        self.qkv_proj = Linear(config.hidden_size, 3 * config.hidden_size,
                               weight_attr=ParamAttr(initializer=init))
        self.out_proj = Linear(config.hidden_size, config.hidden_size,
                               weight_attr=ParamAttr(initializer=init))
        self.dropout_p = config.attention_probs_dropout_prob
        self.use_flash_attention = config.use_flash_attention
        self.resid_dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x, cache=None):
        b, n = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = manip.reshape(qkv, [b, n, 3, self.num_heads, self.head_dim])
        q = manip.squeeze(manip.slice(qkv, [2], [0], [1]), 2)
        k = manip.squeeze(manip.slice(qkv, [2], [1], [2]), 2)
        v = manip.squeeze(manip.slice(qkv, [2], [2], [3]), 2)
        if cache is not None and hasattr(cache, "block_tables"):
            # paged serving cache (serving/cache.py PagedCacheView): the
            # continuous-batching engine's block-pool memory — sequences
            # of different lengths share one pool via per-slot block
            # tables, so ONE compiled decode step serves every tenant mix
            return self._paged_decode_step(q, k, v, cache, b, n)
        if cache is not None and len(cache) == 3:
            # static serving cache: preallocated [B, T, H, D] buffers + a
            # write position — one compiled decode step serves every token
            # (reference analog: the fused_multi_transformer serving cache,
            # inference/api/analysis_predictor.h:95 clientele)
            return self._decode_step(q, k, v, cache, b, n)
        if cache is not None:
            pk, pv = cache
            k = manip.concat([pk, k], axis=1)
            v = manip.concat([pv, v], axis=1)
            cache = (k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.dropout_p if self.training else 0.0,
            training=self.training,
            use_flash_attention=self.use_flash_attention)
        out = manip.reshape(out, [b, n, self.hidden_size])
        out = self.resid_dropout(self.out_proj(out))
        return (out, cache) if cache is not None else out

    def _decode_step(self, q, k, v, cache, b, n):
        """Single-token attention against a static KV buffer: write the new
        K/V at `pos`, attend over positions <= pos. All shapes static, so
        XLA compiles ONE program for the whole decode loop."""
        k_buf, v_buf, pos = cache
        head_dim = self.head_dim

        def fn(qv, kv, vv, kbv, vbv, posv):
            z = jnp.asarray(0, jnp.int32)   # match index dtypes under x64
            start = (z, posv.astype(jnp.int32), z, z)
            kbv = jax.lax.dynamic_update_slice(kbv, kv.astype(kbv.dtype),
                                               start)
            vbv = jax.lax.dynamic_update_slice(vbv, vv.astype(vbv.dtype),
                                               start)
            t = kbv.shape[1]
            # [B,H,n,D] x [B,H,D,T] -> scores [B,H,n,T]
            qh = jnp.transpose(qv, (0, 2, 1, 3))
            kh = jnp.transpose(kbv, (0, 2, 3, 1))
            scores = jnp.einsum("bhnd,bhdt->bhnt", qh, kh) \
                / jnp.sqrt(jnp.asarray(head_dim, qv.dtype))
            # row r of this chunk sits at absolute position pos+r and may
            # attend to every position <= pos+r (causal within the chunk)
            n_in = qv.shape[1]
            row_pos = posv + jnp.arange(n_in)[None, None, :, None]
            valid = jnp.arange(t)[None, None, None, :] <= row_pos
            scores = jnp.where(valid, scores, jnp.asarray(-1e9, qv.dtype))
            probs = jax.nn.softmax(scores.astype(jnp.float32),
                                   axis=-1).astype(qv.dtype)
            vh = jnp.transpose(vbv, (0, 2, 1, 3))
            out = jnp.einsum("bhnt,bhtd->bhnd", probs, vh)
            return jnp.transpose(out, (0, 2, 1, 3)), kbv, vbv

        from ...ops._helpers import call_op_multi, ensure_tensor, const_input
        # the write position rides as a dispatch input: a captured
        # per-step position array would re-key the op on every token
        out, new_k, new_v = call_op_multi(
            "gpt_decode_attention", fn,
            (ensure_tensor(q), ensure_tensor(k), ensure_tensor(v),
             k_buf, v_buf, const_input(pos)), num_outputs=3)
        out = manip.reshape(out, [b, n, self.hidden_size])
        out = self.out_proj(out)
        return out, (new_k, new_v, pos)


    def _paged_decode_step(self, q, k, v, cache, b, n):
        """Single-token attention against the paged block pool: write this
        step's K/V at each slot's write position, stream that slot's blocks
        by table (blockwise online softmax — or the dense gather oracle),
        attend over positions <= seq_len. Shapes are fixed by
        (max_batch, max_blocks, block_size), so the serving engine compiles
        ONE program for every batch composition."""
        if n != 1:
            raise ValueError(
                "paged decode is single-token; prefill goes through the "
                f"dynamic-cache path (got a {n}-token chunk)")
        from ...nn.functional.attention import (paged_decode_attention,
                                                resolve_paged_kernel)
        from ...ops._helpers import call_op_multi, ensure_tensor
        block_size = cache.block_size
        # the RESOLVED variant is captured in the op fn's closure — that
        # is what keys it into the per-op dispatch cache, so a
        # FLAGS_serve_attention_kernel flip re-keys instead of replaying
        # the previous variant's executable. An engine-owned cache view
        # pins the variant it resolved at construction.
        variant = cache.kernel
        if variant is None:
            variant = resolve_paged_kernel(head_dim=self.head_dim,
                                           block_size=block_size)

        quantized = cache.k_scales is not None

        def fn(qv, kv, vv, kp, vp, tab, lens, act, ksc=None, vsc=None):
            return paged_decode_attention(
                qv, kv, vv, kp, vp, tab, lens, act, block_size,
                k_scales=ksc, v_scales=vsc, kernel=variant)

        # int8 KV: the scale side-tables are dispatch INPUTS (never
        # closure captures) and flow back out with the pools — the
        # differing arity also keys the two modes apart in the cache
        inputs = (ensure_tensor(q), ensure_tensor(k), ensure_tensor(v),
                  ensure_tensor(cache.k_pool), ensure_tensor(cache.v_pool),
                  ensure_tensor(cache.block_tables),
                  ensure_tensor(cache.seq_lens), ensure_tensor(cache.active))
        if quantized:
            inputs += (ensure_tensor(cache.k_scales),
                       ensure_tensor(cache.v_scales))
        outs = call_op_multi("gpt_paged_decode_attention", fn, inputs,
                             num_outputs=5 if quantized else 3)
        out = manip.reshape(outs[0], [b, n, self.hidden_size])
        out = self.out_proj(out)
        new_scales = (outs[3]._value, outs[4]._value) if quantized else ()
        return out, cache.updated(outs[1]._value, outs[2]._value,
                                  *new_scales)


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, config.initializer_range)
        self.fc_in = Linear(config.hidden_size, config.intermediate_size,
                            weight_attr=ParamAttr(initializer=init))
        self.fc_out = Linear(config.intermediate_size, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x),
                                               approximate=True)))


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)

    def forward(self, x, cache=None):
        if cache is not None:
            a, cache = self.attn(self.ln_1(x), cache)
        else:
            a = self.attn(self.ln_1(x))
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return (x, cache) if cache is not None else x


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = I.Normal(0.0, config.initializer_range)
        self.wte = Embedding(config.vocab_size, config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.wpe = Embedding(config.max_position_embeddings,
                             config.hidden_size,
                             weight_attr=ParamAttr(initializer=init))
        self.drop = Dropout(config.hidden_dropout_prob)
        self.h = LayerList([GPTBlock(config)
                            for _ in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size,
                              epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, caches=None):
        b, n = input_ids.shape[0], input_ids.shape[1]
        paged = caches is not None and hasattr(caches[0], "block_tables")
        static_cache = caches is not None and not paged \
            and len(caches[0]) == 3
        if paged:
            past_len = None
        elif static_cache:
            past = caches[0][2]._value           # current write position
            past_len = None
        else:
            past_len = caches[0][0].shape[1] if caches is not None else 0
        if position_ids is None and paged:
            # continuous batching: every slot sits at its OWN position
            # (seq_lens), unlike the dense static cache's shared scalar
            raw = caches[0].seq_lens
            lens = jnp.asarray(getattr(raw, "_value", raw)).astype(jnp.int32)
            pos = Tensor(lens[:, None]
                         + jnp.arange(n, dtype=jnp.int32)[None, :])
        elif position_ids is None and static_cache:
            pos = Tensor(past.astype(jnp.int32)
                         + jnp.arange(n, dtype=jnp.int32)[None, :])
        elif position_ids is None:
            pos = Tensor(jnp.arange(past_len, past_len + n,
                                    dtype=jnp.int32)[None, :])
        else:
            pos = position_ids
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if caches is None:
            for block in self.h:
                x = block(x)
            return self.ln_f(x)
        new_caches = []
        for block, cache in zip(self.h, caches):
            x, c = block(x, cache)
            new_caches.append(c)
        return self.ln_f(x), new_caches


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def gen_caches(self, batch_size, dtype=None):
        """Empty KV caches for incremental decoding. dtype defaults to the
        model's parameter dtype (so bf16 models get bf16 caches)."""
        from ...ops.creation import zeros
        cfg = self.config
        if dtype is None:
            params = self.parameters()
            dtype = params[0].dtype if params else "float32"
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        return [(zeros([batch_size, 0, cfg.num_attention_heads, head_dim],
                       dtype),
                 zeros([batch_size, 0, cfg.num_attention_heads, head_dim],
                       dtype))
                for _ in range(cfg.num_hidden_layers)]

    def forward(self, input_ids, position_ids=None, caches=None):
        if caches is None:
            hidden = self.gpt(input_ids, position_ids)
        else:
            hidden, caches = self.gpt(input_ids, position_ids, caches)
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            # tied: logits = hidden @ wte^T
            logits = F.linear(hidden,
                              manip.transpose(self.gpt.wte.weight, [1, 0]))
        return logits if caches is None else (logits, caches)

    def num_params(self, include_embeddings=True):
        total = 0
        for _, p in self.named_parameters():
            if not include_embeddings and "wte" in _:
                continue
            total += p.size
        return total

    def flops_per_token(self, seq_len, training=True):
        """Model FLOPs per token, PaLM-appendix counting: training =
        6N + 12*L*h*s (fwd+bwd), inference = 2N + 4*L*h*s."""
        n = self.num_params()
        cfg = self.config
        attn_fwd = 4 * cfg.num_hidden_layers * cfg.hidden_size * seq_len
        if training:
            return 6 * n + 3 * attn_fwd
        return 2 * n + attn_fwd

    def gen_static_caches(self, batch_size, max_len, dtype=None):
        """Preallocated serving caches: per layer (k_buf, v_buf) of shape
        [B, max_len, H, D] plus a shared position scalar — the static-shape
        counterpart of gen_caches for the compiled decode loop."""
        cfg = self.config
        if dtype is None:
            params = self.parameters()
            dtype = params[0]._value.dtype if params else jnp.float32
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        shape = (batch_size, max_len, cfg.num_attention_heads, head_dim)
        return [(Tensor(jnp.zeros(shape, dtype)),
                 Tensor(jnp.zeros(shape, dtype)))
                for _ in range(cfg.num_hidden_layers)]

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 top_k=1, top_p=1.0, temperature=1.0, seed=0):
        """Batched autoregressive decoding, compiled as ONE XLA program:
        prefill on the full prompt, then a lax.scan over decode steps
        against static KV buffers (shapes fixed at [B, P + N]).

        Reference analog: the serving decode the reference drives through
        AnalysisPredictor + fused_multi_transformer
        (inference/api/analysis_predictor.h:95, incubate FusedMultiTransformer);
        greedy (do_sample=False) or top-k/top-p temperature sampling
        (top_p >= 1 disables the nucleus filter; the mask reuses the
        serving sampler's `apply_top_p`, so both paths keep one
        definition of the nucleus rule).
        Returns the generated ids, [B, max_new_tokens].
        """
        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        b, p = ids.shape
        n_new = int(max_new_tokens)
        total = p + n_new
        params = self.parameters()
        was_training = self.training
        self.eval()

        def swap_call(pvals, *args, **kw):
            saved = [pp._value for pp in params]
            try:
                for pp, vv in zip(params, pvals):
                    pp._value = vv
                from ...framework.autograd import set_grad_enabled
                with set_grad_enabled(False):
                    return self.forward(*args, **kw)
            finally:
                for pp, vv in zip(params, saved):
                    pp._value = vv

        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dt = params[0]._value.dtype

        def decode(pvals, prompt, key):
            # prefill: dynamic-cache forward over the prompt (static shapes
            # because the prompt length is static)
            empty = [(Tensor(jnp.zeros((b, 0, cfg.num_attention_heads,
                                        head_dim), dt)),) * 2
                     for _ in range(cfg.num_hidden_layers)]
            logits, caches = swap_call(pvals,
                                       Tensor(prompt, stop_gradient=True),
                                       caches=[tuple(c) for c in empty])
            # pack prompt KV into the static buffers
            bufs = []
            for (ck, cv) in caches:
                kb = jnp.zeros((b, total, cfg.num_attention_heads, head_dim),
                               dt).at[:, :p].set(ck._value)
                vb = jnp.zeros((b, total, cfg.num_attention_heads, head_dim),
                               dt).at[:, :p].set(cv._value)
                bufs.append((kb, vb))
            last = logits._value[:, -1, :]

            def pick(lg, k2):
                if not do_sample:
                    return jnp.argmax(lg, axis=-1).astype(jnp.int32)
                lg = lg.astype(jnp.float32) / max(temperature, 1e-6)
                if top_k and top_k > 0:
                    kth = jnp.sort(lg, axis=-1)[:, -int(top_k)][:, None]
                    lg = jnp.where(lg < kth, -jnp.inf, lg)
                if top_p is not None and float(top_p) < 1.0:
                    from ...serving.sampling import apply_top_p
                    lg = apply_top_p(lg, jnp.full((lg.shape[0],),
                                                  float(top_p),
                                                  jnp.float32))
                return jax.random.categorical(k2, lg, axis=-1) \
                    .astype(jnp.int32)

            tok0 = pick(last, jax.random.fold_in(key, 0))

            def step(carry, i):
                tok, bufs, key = carry
                pos = p + i
                static = [(Tensor(kb), Tensor(vb),
                           Tensor(jnp.asarray(pos, jnp.int32)))
                          for kb, vb in bufs]
                lg, new_caches = swap_call(
                    pvals, Tensor(tok[:, None], stop_gradient=True),
                    caches=static)
                bufs = [(nk._value, nv._value)
                        for nk, nv, _pos in new_caches]
                nxt = pick(lg._value[:, -1, :],
                           jax.random.fold_in(key, i + 1))
                return (nxt, bufs, key), tok

            (last_tok, _, _), toks = jax.lax.scan(
                step, (tok0, bufs, key), jnp.arange(n_new - 1))
            out = jnp.concatenate([jnp.transpose(toks, (1, 0)),
                                   last_tok[:, None]], axis=1)
            return out

        try:
            # cache the compiled decode per shape/flag signature — a fresh
            # jax.jit wrapper every call would retrace AND recompile
            if not hasattr(self, "_gen_cache"):
                self._gen_cache = {}
            sig = (b, p, n_new, bool(do_sample), int(top_k),
                   float(top_p if top_p is not None else 1.0),
                   float(temperature))
            jitted = self._gen_cache.get(sig)
            if jitted is None:
                jitted = jax.jit(decode)
                self._gen_cache[sig] = jitted
            out = jitted([pp._value for pp in params], ids,
                         jax.random.PRNGKey(seed))
        finally:
            if was_training:
                self.train()
        return Tensor(out, stop_gradient=True)


class GPTDecodeStep(Layer):
    """One serving decode step as a saveable artifact: (tokens [B,1],
    k_bufs [L,B,T,H,D], v_bufs, pos scalar) -> (logits [B,1,V], new_k,
    new_v). jit.save(...) of this layer yields the StableHLO program the
    inference Predictor replays per generated token — the TPU-native analog
    of running the reference's fused_multi_transformer decode through
    AnalysisPredictor (inference/api/analysis_predictor.h:95)."""

    def __init__(self, model: "GPTForCausalLM"):
        super().__init__()
        self.model = model

    def forward(self, tokens, k_bufs, v_bufs, pos):
        cfg = self.model.config
        caches = []
        for l in range(cfg.num_hidden_layers):
            kb = manip.squeeze(manip.slice(k_bufs, [0], [l], [l + 1]), 0)
            vb = manip.squeeze(manip.slice(v_bufs, [0], [l], [l + 1]), 0)
            caches.append((kb, vb, pos))
        logits, new_caches = self.model(tokens, caches=caches)
        new_k = manip.stack([c[0] for c in new_caches])
        new_v = manip.stack([c[1] for c in new_caches])
        return logits, new_k, new_v


class GPTPretrainingCriterion(Layer):
    """Language-model loss (next-token cross entropy)."""

    def __init__(self, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        b, n, v = logits.shape
        flat = manip.reshape(logits, [b * n, v])
        flat_lab = manip.reshape(labels, [b * n])
        return F.cross_entropy(flat, flat_lab,
                               ignore_index=self.ignore_index)


# ---------------------------------------------------------------------------
# Hybrid-parallel sharding rules
# ---------------------------------------------------------------------------

def shard_gpt(model: GPTForCausalLM, mesh, dtype=None):
    """Annotate GPT parameters with NamedShardings over `mesh`.

    Megatron placement (SURVEY.md §7 row "mp layers"): qkv and fc_in are
    column-parallel (out-dim on "model"), out_proj and fc_out are row-parallel
    (in-dim on "model"), embeddings vocab-parallel. Remaining axes are left to
    the partitioner; optimizer state inherits shardings from params and is
    further sharded over "sharding" by the sharded optimizer.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(p, spec):
        if p is None:
            return
        val = p._value
        if dtype is not None:
            val = val.astype(dtype)
        p._value = jax.device_put(val, NamedSharding(mesh, spec))

    rules = [
        ("wte.weight", P("model", None)),
        ("wpe.weight", P()),
        ("qkv_proj.weight", P(None, "model")),
        ("qkv_proj.bias", P("model")),
        ("out_proj.weight", P("model", None)),
        ("out_proj.bias", P()),
        ("fc_in.weight", P(None, "model")),
        ("fc_in.bias", P("model")),
        ("fc_out.weight", P("model", None)),
        ("fc_out.bias", P()),
        ("lm_head.weight", P(None, "model")),
        ("ln_", P()),
    ]
    for name, p in model.named_parameters():
        spec = None
        for pat, s in rules:
            if pat in name:
                spec = s
                break
        put(p, spec if spec is not None else P())
    return model


# ---------------------------------------------------------------------------
# Pipeline-parallel decomposition
# ---------------------------------------------------------------------------

class GPTEmbeddingPipe(Layer):
    """Prologue stage: token + position embedding (shares the model's
    wte/wpe/drop sublayers). Reference analog: the embedding LayerDesc in the
    reference GPT pipeline models (fleet pp_layers SharedLayerDesc for tied
    embeddings)."""

    def __init__(self, model: "GPTForCausalLM"):
        super().__init__()
        self.wte = model.gpt.wte
        self.wpe = model.gpt.wpe
        self.drop = model.gpt.drop

    def forward(self, input_ids):
        n = input_ids.shape[1]
        pos = Tensor(jnp.arange(0, n, dtype=jnp.int32)[None, :],
                     stop_gradient=True)
        return self.drop(self.wte(input_ids) + self.wpe(pos))


class GPTHeadPipe(Layer):
    """Epilogue stage: final LayerNorm + (tied) LM head. The tied wte weight
    is the SAME Parameter object as the embedding's — PipelineTrainStep
    dedupes by identity so its gradient accumulates from both uses."""

    def __init__(self, model: "GPTForCausalLM"):
        super().__init__()
        self.ln_f = model.gpt.ln_f
        self.lm_head = model.lm_head
        self._wte = model.gpt.wte

    def forward(self, x):
        h = self.ln_f(x)
        if self.lm_head is not None:
            return self.lm_head(h)
        return F.linear(h, manip.transpose(self._wte.weight, [1, 0]))


def gpt_pipeline_layers(model: "GPTForCausalLM"):
    """Flatten a GPTForCausalLM into the sequential layer list consumed by
    PipelineTrainStep: [embedding, block*L, ln_f+head]. The transformer
    blocks form the homogeneous run that gets sharded over the "pipe" axis."""
    return ([GPTEmbeddingPipe(model)] + list(model.gpt.h)
            + [GPTHeadPipe(model)])
