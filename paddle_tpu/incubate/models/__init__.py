from . import gpt  # noqa: F401
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, GPTPretrainingCriterion,
    gpt2_124m, gpt2_355m, gpt3_1p3b, gpt3_6p7b, shard_gpt,
    GPTEmbeddingPipe, GPTHeadPipe, gpt_pipeline_layers, GPTDecodeStep,
)
