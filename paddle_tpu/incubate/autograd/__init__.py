"""Primitive-op AD. Reference analog:
python/paddle/incubate/autograd/primapi.py (:22 forward_grad, :105 grad).

TPU-first: instead of lowering to a primitive-op program and transforming it
(the reference's prim2orig pipeline), the recorded eager graph is replayed as
a pure jax function (framework.autograd.replay_pure) and jax.jvp / jax.vjp
are the primitive transforms. Everything XLA-compiles.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...framework.autograd import replay_pure, reachable_leaves
from ...framework.autograd import grad as _eager_grad
from ...autograd import jvp, vjp, jacobian, hessian  # noqa: F401

__all__ = ["forward_grad", "grad", "jvp", "vjp", "jacobian", "hessian"]


def _listify(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode gradients (JVP) of outputs w.r.t. inputs over the
    recorded eager graph. Reference analog: primapi.py:22 forward_grad.

    grad_inputs: tangent seeds aligned with `inputs` (ones by default).
    Returns tangents aligned with `outputs`, dispatched through the op
    funnel so they are themselves differentiable.
    """
    from ...ops.dispatch import call_op_multi
    outputs = _listify(outputs)
    inputs = _listify(inputs)
    if grad_inputs is None:
        tangents = [Tensor(jnp.ones(t.shape, t._value.dtype),
                           stop_gradient=True) for t in inputs]
    else:
        tangents = [g if isinstance(g, Tensor)
                    else Tensor(jnp.asarray(g), stop_gradient=True)
                    for g in _listify(grad_inputs)]
    # other leaves (model params) ride along as op arguments so the tangent
    # stays differentiable w.r.t. them (mixed forward-over-reverse d2y/dxdW)
    leaves = reachable_leaves(outputs, {id(t) for t in inputs})
    F = replay_pure(outputs, inputs + leaves)
    n, nl = len(inputs), len(leaves)

    def J(*vals):
        primals = vals[:n]
        leaf_vals = vals[n:n + nl]
        tans = vals[n + nl:]
        _, out_tangents = jax.jvp(lambda *iv: F(*iv, *leaf_vals),
                                  primals, tans)
        return tuple(out_tangents)

    outs = call_op_multi("forward_grad_replay", J,
                         inputs + leaves + tangents,
                         num_outputs=len(outputs))
    return outs if len(outs) > 1 else outs[0]


def grad(outputs, inputs, grad_outputs=None):
    """Reverse-mode gradients over the recorded graph, differentiable
    (primapi.py:105 semantics — always create_graph)."""
    res = _eager_grad(outputs, inputs, grad_outputs=grad_outputs,
                      create_graph=True, allow_unused=True)
    return res if len(res) > 1 else res[0]
