"""Primitive-op AD. Reference analog:
python/paddle/incubate/autograd/primapi.py (:22 forward_grad, :105 grad).

TPU-first: instead of lowering to a primitive-op program and transforming it
(the reference's prim2orig pipeline), the recorded eager graph is replayed as
a pure jax function (framework.autograd.replay_pure) and jax.jvp / jax.vjp
are the primitive transforms. Everything XLA-compiles.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...framework.autograd import replay_pure, reachable_leaves
from ...framework.autograd import grad as _eager_grad
from ...autograd import jvp, vjp, jacobian, hessian  # noqa: F401

__all__ = ["forward_grad", "grad", "jvp", "vjp", "jacobian", "hessian"]


def _listify(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode gradients (JVP) of outputs w.r.t. inputs over the
    recorded eager graph. Reference analog: primapi.py:22 forward_grad.

    grad_inputs: tangent seeds aligned with `inputs` (ones by default).
    Returns tangents aligned with `outputs`, dispatched through the op
    funnel so they are themselves differentiable.
    """
    from ...ops.dispatch import call_op_multi
    outputs = _listify(outputs)
    inputs = _listify(inputs)
    if grad_inputs is None:
        tangents = [Tensor(jnp.ones(t.shape, t._value.dtype),
                           stop_gradient=True) for t in inputs]
    else:
        tangents = [g if isinstance(g, Tensor)
                    else Tensor(jnp.asarray(g), stop_gradient=True)
                    for g in _listify(grad_inputs)]
    # other leaves (model params) ride along as op arguments so the tangent
    # stays differentiable w.r.t. them (mixed forward-over-reverse d2y/dxdW)
    leaves = reachable_leaves(outputs, {id(t) for t in inputs})
    F = replay_pure(outputs, inputs + leaves)
    n, nl = len(inputs), len(leaves)

    def J(*vals):
        primals = vals[:n]
        leaf_vals = vals[n:n + nl]
        tans = vals[n + nl:]
        _, out_tangents = jax.jvp(lambda *iv: F(*iv, *leaf_vals),
                                  primals, tans)
        return tuple(out_tangents)

    outs = call_op_multi("forward_grad_replay", J,
                         inputs + leaves + tangents,
                         num_outputs=len(outputs))
    return outs if len(outs) > 1 else outs[0]


def grad(outputs, inputs, grad_outputs=None):
    """Reverse-mode gradients over the recorded graph, differentiable
    (primapi.py:105 semantics — always create_graph)."""
    res = _eager_grad(outputs, inputs, grad_outputs=grad_outputs,
                      create_graph=True, allow_unused=True)
    return res if len(res) > 1 else res[0]


class Jacobian:
    """Lazy Jacobian matrix view (reference:
    incubate/autograd/functional.py Jacobian — computed on first index).
    J has shape [M, N] (or [B, M, N] with is_batched) and supports
    numpy-style slicing."""

    def __init__(self, func, xs, is_batched=False):
        self._func = func
        self._xs = xs
        self._is_batched = is_batched
        self._mat = None

    def _materialize(self):
        if self._mat is None:
            xs_l = self._xs if isinstance(self._xs, (list, tuple)) \
                else [self._xs]
            j = jacobian(self._func, self._xs)
            blocks = [j] if isinstance(j, Tensor) else list(j)
            mats = []
            for blk, x in zip(blocks, xs_l):
                v = blk._value
                if self._is_batched:
                    # [B, M, B, N] diag -> [B, M, N]
                    b = v.shape[0]
                    v = jnp.stack([v[i, :, i, :] for i in range(b)])
                else:
                    # flatten to [M, Ni] with Ni = this input's size
                    ni = int(np.prod(x._value.shape))
                    v = v.reshape(-1, ni)
                mats.append(v)
            # multiple inputs: hstack the column blocks (reference
            # functional.py Jacobian over concat'd xs)
            self._mat = mats[0] if len(mats) == 1 else \
                jnp.concatenate(mats, axis=-1)
        return self._mat

    @property
    def shape(self):
        return list(self._materialize().shape)

    def __getitem__(self, idx):
        return Tensor(self._materialize()[idx])


class Hessian:
    """Lazy Hessian matrix view (reference Hessian — symmetric [N, N])."""

    def __init__(self, func, xs, is_batched=False):
        self._func = func
        self._xs = xs
        self._is_batched = is_batched
        self._mat = None

    def _materialize(self):
        if self._mat is None:
            if isinstance(self._xs, (list, tuple)):
                # multi-input: assemble the full [N, N] from the nested
                # block structure h[i][j] (reference concatenates blocks)
                h = hessian(self._func, list(self._xs))
                sizes = [int(np.prod(x._value.shape)) for x in self._xs]
                rows = []
                for i, hi in enumerate(h):
                    row = [jnp.reshape(
                        hij._value if isinstance(hij, Tensor) else hij,
                        (sizes[i], sizes[j]))
                        for j, hij in enumerate(hi)]
                    rows.append(jnp.concatenate(row, axis=1))
                self._mat = jnp.concatenate(rows, axis=0)
            else:
                h = hessian(self._func, self._xs)
                v = h._value if isinstance(h, Tensor) else h
                if self._is_batched:
                    # [B, N, B, N] per-batch diag -> [B, N, N]
                    b = self._xs._value.shape[0]
                    n = int(np.prod(self._xs._value.shape[1:]))
                    v = v.reshape(b, n, b, n)
                    self._mat = jnp.stack([v[i, :, i, :] for i in range(b)])
                else:
                    n = int(np.prod(self._xs._value.shape))
                    self._mat = v.reshape(n, n)
        return self._mat

    @property
    def shape(self):
        return list(self._materialize().shape)

    def __getitem__(self, idx):
        return Tensor(self._materialize()[idx])


_prim_enabled = False


def enable_prim():
    """Switch AD to primitive-op mode (reference primapi: lowers the
    program to prim ops). Here AD is ALWAYS primitive — replay_pure +
    jax.jvp/vjp over jaxpr primitives — so this records intent only."""
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def prim_enabled():
    return _prim_enabled


__all__ += ["Jacobian", "Hessian", "enable_prim", "disable_prim",
            "prim_enabled"]
