"""Primitive-op AD (forward mode). Reference analog:
python/paddle/incubate/autograd/primapi.py (:22 forward_grad, :105 grad).
TPU-first: jax.jvp/jax.grad are the primitive transforms."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...autograd import grad, jvp as _jvp  # noqa: F401

__all__ = ["forward_grad", "grad", "jvp"]

jvp = _jvp


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode gradients (JVP) of outputs w.r.t. inputs."""
    raise NotImplementedError(
        "forward_grad over recorded eager graphs is not supported; use "
        "paddle_tpu.autograd.jvp(func, xs, v) with an explicit function")
