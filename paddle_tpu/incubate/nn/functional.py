"""incubate.nn.functional — fused-op functional entry points.

Reference analog: python/paddle/incubate/nn/functional/ (fused_transformer.py
fused_bias_dropout_residual_layer_norm, fused_matmul_bias, ...) over the
fused CUDA ops; here they route to Pallas kernels when eligible and XLA
otherwise.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, call_op, const_input
from ...kernels import fused_ln as _fused_ln
from ...kernels import cross_entropy as _fused_ce
from ...ops.math import matmul as _matmul

__all__ = ["fused_bias_dropout_residual_layer_norm",
           "fused_softmax_cross_entropy", "fused_linear"]


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode="upscale_in_train",
        name=None):
    """y = LayerNorm(residual + dropout(x + bias)).

    Reference analog: incubate/nn/functional/fused_transformer.py over
    fused_bias_dropout_residual_layer_norm_op.cu.
    """
    x = ensure_tensor(x)
    residual = ensure_tensor(residual)
    d = x.shape[-1]
    bias_t = ensure_tensor(bias) if bias is not None else None
    scale_t = ensure_tensor(ln_scale) if ln_scale is not None else None
    shift_t = ensure_tensor(ln_bias) if ln_bias is not None else None

    # dropout is a real op while training, and still rescales at inference
    # under downscale_in_infer — both cases route through F.dropout (XLA)
    needs_dropout = dropout_rate > 0.0 and (
        training or mode == "downscale_in_infer")
    if needs_dropout or not _fused_ln.is_eligible(x._value, d):
        from ...nn import functional as F
        h = x if bias_t is None else x + bias_t
        if needs_dropout:
            h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
        return F.layer_norm(h + residual, [d], weight=scale_t, bias=shift_t,
                            epsilon=ln_epsilon)

    args = [x, residual]
    has_b, has_s, has_sh = (bias_t is not None, scale_t is not None,
                            shift_t is not None)

    def fn(xv, rv, *rest):
        lead = xv.shape[:-1]
        x2 = xv.reshape(-1, d)
        r2 = rv.reshape(-1, d)
        vals = list(rest)
        # absent affine terms are trace-time constants built in-graph —
        # capturing prebuilt arrays would make the op un-keyable (R1)
        bb = vals.pop(0) if has_b else jnp.zeros((d,), jnp.float32)
        sc = vals.pop(0) if has_s else jnp.ones((d,), jnp.float32)
        sh = vals.pop(0) if has_sh else jnp.zeros((d,), jnp.float32)
        out = _fused_ln.fused_bias_residual_layer_norm(
            x2, r2, bb, sc, sh, ln_epsilon)
        return out.reshape(lead + (d,))

    for t in (bias_t, scale_t, shift_t):
        if t is not None:
            args.append(t)
    return call_op("fused_bias_dropout_residual_layer_norm", fn, tuple(args))


def fused_softmax_cross_entropy(logits, label, ignore_index=-100,
                                reduction="mean", name=None):
    """Vocab-blocked fused CE. Reference analog:
    c_softmax_with_cross_entropy / softmax_with_cross_entropy.

    Unlike nn.functional.cross_entropy (gated by
    FLAGS_use_fused_cross_entropy), this explicit entry point always uses the
    Pallas kernel when the device/shape supports it, falling back to XLA
    otherwise."""
    logits = ensure_tensor(logits)
    label = ensure_tensor(label)
    lab_v = label._value

    if _fused_ce.is_eligible(logits._value, lab_v, force=True):
        lab_in = const_input(label)

        def fn(lg, lv):
            lab_idx = jnp.clip(lv, 0, lg.shape[1] - 1).astype(jnp.int32)
            nll = _fused_ce.fused_softmax_cross_entropy(lg, lab_idx)
            return _fused_ce.masked_reduce(nll, lv, ignore_index,
                                           reduction)
        return call_op("fused_softmax_cross_entropy", fn, (logits, lab_in))

    from ...nn.functional import cross_entropy
    return cross_entropy(logits, label, ignore_index=ignore_index,
                         reduction=reduction)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Matmul + bias epilogue (XLA fuses this natively on the MXU).
    Reference analog: fused_gemm_epilogue_op.cc (cublasLt epilogue)."""
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    if bias is None:
        def fn(a, w):
            wm = w.T if transpose_weight else w
            return a @ wm
        return call_op("fused_linear", fn, (x, weight))

    def fn(a, w, b):
        wm = w.T if transpose_weight else w
        return a @ wm + b
    return call_op("fused_linear", fn, (x, weight, ensure_tensor(bias)))


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias epilogue (reference: functional/fused_matmul_bias.py
    over fused_gemm_epilogue_op.cc/cublasLt). XLA fuses the epilogue."""
    from ...ops import math as pmath
    out = pmath.matmul(ensure_tensor(x), ensure_tensor(y),
                       transpose_x=transpose_x, transpose_y=transpose_y)
    if bias is not None:
        out = out + ensure_tensor(bias)
    return out


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-05, qkv_bias=None, linear_bias=None,
        cache_kv=None, attn_mask=None, dropout_rate=0.5,
        attn_dropout_rate=0.5, ln_epsilon=1e-05, training=True,
        mode="upscale_in_train", ring_id=-1, add_residual=True, name=None):
    """Functional fused attention (reference: incubate/nn/functional/
    fused_transformer.py fused_multi_head_attention over
    fused_attention_op.cu). qkv_weight [3, H, D, E]; the attention core is
    the flash/XLA path of F.scaled_dot_product_attention."""
    import paddle_tpu.nn.functional as F
    from ...ops import manipulation as manip
    if cache_kv is not None:
        raise NotImplementedError(
            "fused_multi_head_attention cache_kv: use the compiled decode "
            "path (incubate.models.GPTDecodeStep / model.generate()) — the "
            "static-KV serving cache lives there on TPU")
    xt = ensure_tensor(x)
    qkvw = ensure_tensor(qkv_weight)
    n_heads, head_dim = qkvw.shape[1], qkvw.shape[2]
    embed = qkvw.shape[3]
    residual = xt
    if pre_layer_norm:
        xt = F.layer_norm(xt, [embed], weight=pre_ln_scale,
                          bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    # [B, N, E] @ [E, 3*H*D]
    wmat = manip.reshape(manip.transpose(qkvw, [3, 0, 1, 2]),
                         [embed, 3 * n_heads * head_dim])
    qkv = _matmul(xt, wmat)
    if qkv_bias is not None:
        qkv = qkv + manip.reshape(ensure_tensor(qkv_bias),
                                  [3 * n_heads * head_dim])
    b, n = xt.shape[0], xt.shape[1]
    qkv = manip.reshape(qkv, [b, n, 3, n_heads, head_dim])
    q = manip.squeeze(manip.slice(qkv, [2], [0], [1]), 2)
    k = manip.squeeze(manip.slice(qkv, [2], [1], [2]), 2)
    v = manip.squeeze(manip.slice(qkv, [2], [2], [3]), 2)
    ctx = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0,
        training=training)
    ctx = manip.reshape(ctx, [b, n, n_heads * head_dim])
    out = _matmul(ctx, ensure_tensor(linear_weight))
    if linear_bias is not None:
        out = out + ensure_tensor(linear_bias)
    if dropout_rate and training:
        out = F.dropout(out, p=dropout_rate, training=training)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, [embed], weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """Functional fused FFN (reference fused_feedforward over
    fused_feedforward_op.cu): residual + dropout(act(x@W1+b1)@W2+b2) with
    pre/post LN."""
    import paddle_tpu.nn.functional as F
    xt = ensure_tensor(x)
    d = xt.shape[-1]
    residual = xt
    if pre_layer_norm:
        xt = F.layer_norm(xt, [d], weight=ln1_scale, bias=ln1_bias,
                          epsilon=ln1_epsilon)
    h = _matmul(xt, ensure_tensor(linear1_weight))
    if linear1_bias is not None:
        h = h + ensure_tensor(linear1_bias)
    h = getattr(F, activation)(h)
    if dropout1_rate and training:
        h = F.dropout(h, p=dropout1_rate, training=training)
    h = _matmul(h, ensure_tensor(linear2_weight))
    if linear2_bias is not None:
        h = h + ensure_tensor(linear2_bias)
    if dropout2_rate and training:
        h = F.dropout(h, p=dropout2_rate, training=training)
    out = residual + h if add_residual else h
    if not pre_layer_norm:
        out = F.layer_norm(out, [d], weight=ln2_scale, bias=ln2_bias,
                           epsilon=ln2_epsilon)
    return out


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-05, cache_kvs=None, pre_caches=None, time_step=None,
        attn_mask=None, dropout_rate=0.0, activation="gelu",
        training=False, mode="upscale_in_train", trans_qkvw=True,
        ring_id=-1, name=None):
    """Stacked fused transformer blocks (reference fused_multi_transformer
    over fused_multi_transformer_op.cu — the serving path). Applies L
    blocks of fused attention + FFN; cache_kvs, when given, are updated
    per block ([2, B, H, T, D] each, reference layout)."""
    if cache_kvs is not None or time_step is not None:
        raise NotImplementedError(
            "fused_multi_transformer cache_kvs/time_step: use the compiled "
            "decode path (incubate.models.GPTDecodeStep / generate()) for "
            "serving caches on TPU")
    out = ensure_tensor(x)
    n_layers = len(qkv_weights)
    if not trans_qkvw:
        # reference layout [E, 3, H, D] -> the [3, H, D, E] this path uses
        from ...ops import manipulation as _manip
        qkv_weights = [_manip.transpose(ensure_tensor(w), [1, 2, 3, 0])
                       for w in qkv_weights]
    for i in range(n_layers):
        out = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i],
            pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i] if ln_scales else None,
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            pre_ln_epsilon=epsilon,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            ln1_epsilon=epsilon, pre_layer_norm=pre_layer_norm,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, training=training)
    return out


__all__ += ["fused_matmul_bias", "fused_multi_head_attention",
            "fused_feedforward", "fused_multi_transformer"]
