"""incubate.nn.functional — fused-op functional entry points.

Reference analog: python/paddle/incubate/nn/functional/ (fused_transformer.py
fused_bias_dropout_residual_layer_norm, fused_matmul_bias, ...) over the
fused CUDA ops; here they route to Pallas kernels when eligible and XLA
otherwise.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, call_op
from ...kernels import fused_ln as _fused_ln
from ...kernels import cross_entropy as _fused_ce

__all__ = ["fused_bias_dropout_residual_layer_norm",
           "fused_softmax_cross_entropy", "fused_linear"]


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode="upscale_in_train",
        name=None):
    """y = LayerNorm(residual + dropout(x + bias)).

    Reference analog: incubate/nn/functional/fused_transformer.py over
    fused_bias_dropout_residual_layer_norm_op.cu.
    """
    x = ensure_tensor(x)
    residual = ensure_tensor(residual)
    d = x.shape[-1]
    bias_t = ensure_tensor(bias) if bias is not None else None
    scale_t = ensure_tensor(ln_scale) if ln_scale is not None else None
    shift_t = ensure_tensor(ln_bias) if ln_bias is not None else None

    # dropout is a real op while training, and still rescales at inference
    # under downscale_in_infer — both cases route through F.dropout (XLA)
    needs_dropout = dropout_rate > 0.0 and (
        training or mode == "downscale_in_infer")
    if needs_dropout or not _fused_ln.is_eligible(x._value, d):
        from ...nn import functional as F
        h = x if bias_t is None else x + bias_t
        if needs_dropout:
            h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
        return F.layer_norm(h + residual, [d], weight=scale_t, bias=shift_t,
                            epsilon=ln_epsilon)

    ones = jnp.ones((d,), jnp.float32)
    zeros = jnp.zeros((d,), jnp.float32)
    args = [x, residual]
    b_val = bias_t._value if bias_t is not None else zeros
    s_val = scale_t._value if scale_t is not None else ones
    sh_val = shift_t._value if shift_t is not None else zeros

    def fn(xv, rv, *rest):
        lead = xv.shape[:-1]
        x2 = xv.reshape(-1, d)
        r2 = rv.reshape(-1, d)
        vals = list(rest)
        bb = vals.pop(0) if bias_t is not None else b_val
        sc = vals.pop(0) if scale_t is not None else s_val
        sh = vals.pop(0) if shift_t is not None else sh_val
        out = _fused_ln.fused_bias_residual_layer_norm(
            x2, r2, bb, sc, sh, ln_epsilon)
        return out.reshape(lead + (d,))

    for t in (bias_t, scale_t, shift_t):
        if t is not None:
            args.append(t)
    return call_op("fused_bias_dropout_residual_layer_norm", fn, tuple(args))


def fused_softmax_cross_entropy(logits, label, ignore_index=-100,
                                reduction="mean", name=None):
    """Vocab-blocked fused CE. Reference analog:
    c_softmax_with_cross_entropy / softmax_with_cross_entropy.

    Unlike nn.functional.cross_entropy (gated by
    FLAGS_use_fused_cross_entropy), this explicit entry point always uses the
    Pallas kernel when the device/shape supports it, falling back to XLA
    otherwise."""
    logits = ensure_tensor(logits)
    label = ensure_tensor(label)
    lab_v = label._value

    if _fused_ce.is_eligible(logits._value, lab_v, force=True):
        def fn(lg):
            lab_idx = jnp.clip(lab_v, 0, lg.shape[1] - 1).astype(jnp.int32)
            nll = _fused_ce.fused_softmax_cross_entropy(lg, lab_idx)
            return _fused_ce.masked_reduce(nll, lab_v, ignore_index,
                                           reduction)
        return call_op("fused_softmax_cross_entropy", fn, (logits,))

    from ...nn.functional import cross_entropy
    return cross_entropy(logits, label, ignore_index=ignore_index,
                         reduction=reduction)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """Matmul + bias epilogue (XLA fuses this natively on the MXU).
    Reference analog: fused_gemm_epilogue_op.cc (cublasLt epilogue)."""
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    if bias is None:
        def fn(a, w):
            wm = w.T if transpose_weight else w
            return a @ wm
        return call_op("fused_linear", fn, (x, weight))

    def fn(a, w, b):
        wm = w.T if transpose_weight else w
        return a @ wm + b
    return call_op("fused_linear", fn, (x, weight, ensure_tensor(bias)))
