"""Fused transformer layers.

Reference analog: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention :191, FusedFeedForward :478,
FusedTransformerEncoderLayer :706, FusedMultiTransformer :997) over the
hand-fused CUDA ops in fluid/operators/fused/.

TPU-first: "fused" means one jitted region whose attention core is the Pallas
flash kernel and whose FFN/LN/residual chain is one XLA fusion cluster — the
compiler does the epilogue fusion the reference hand-wrote.
"""
from .fused_transformer import (  # noqa: F401
    FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
    FusedMultiTransformer, FusedBiasDropoutResidualLayerNorm, FusedLinear,
)
from . import functional  # noqa: F401
