"""Fused transformer building blocks (see package docstring for design)."""
from __future__ import annotations

import jax.numpy as jnp

from ...nn.layer_base import Layer
from ...nn.initializer_util import materialize_parameter
from ...nn import initializer as I
from ...nn import functional as F
from ...nn.layer.container import LayerList
from ...ops import manipulation as manip

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "FusedBiasDropoutResidualLayerNorm"]


class FusedBiasDropoutResidualLayerNorm(Layer):
    """y = LayerNorm(residual + dropout(x + bias)).

    Reference: incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm over
    fused_bias_dropout_residual_layer_norm_op.cu. Routes to the Pallas
    row-blocked kernel (kernels/fused_ln.py) when eligible.
    """

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = materialize_parameter(
            [embed_dim], bias_attr, self._dtype, is_bias=True)
        self.ln_scale = materialize_parameter(
            [embed_dim], weight_attr, self._dtype,
            default_initializer=I.Constant(1.0))
        self.ln_bias = materialize_parameter(
            [embed_dim], bias_attr, self._dtype, is_bias=True)

    def forward(self, x, residual):
        from .functional import fused_bias_dropout_residual_layer_norm
        return fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)

    def extra_repr(self):
        return f"embed_dim={self.embed_dim}, dropout_rate={self.dropout_rate}"


class FusedMultiHeadAttention(Layer):
    """Reference: incubate/nn/layer/fused_transformer.py:191 over
    fused_attention_op.cu — pre/post-LN + QKV proj + MHA core + out proj +
    residual, as one fused region."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None,
                 pre_ln_bias_attr=None, ln_scale_attr=None, ln_bias_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        self.qkv_weight = materialize_parameter(
            [3, num_heads, self.head_dim, embed_dim], qkv_weight_attr,
            self._dtype, default_initializer=I.XavierUniform())
        self.qkv_bias = materialize_parameter(
            [3, num_heads, self.head_dim], qkv_bias_attr, self._dtype,
            is_bias=True)
        self.linear_weight = materialize_parameter(
            [embed_dim, embed_dim], linear_weight_attr, self._dtype,
            default_initializer=I.XavierUniform())
        self.linear_bias = materialize_parameter(
            [embed_dim], linear_bias_attr, self._dtype, is_bias=True)
        self.pre_ln_scale = materialize_parameter(
            [embed_dim], pre_ln_scale_attr, self._dtype,
            default_initializer=I.Constant(1.0))
        self.pre_ln_bias = materialize_parameter(
            [embed_dim], pre_ln_bias_attr, self._dtype, is_bias=True)
        self.ln_scale = materialize_parameter(
            [embed_dim], ln_scale_attr, self._dtype,
            default_initializer=I.Constant(1.0))
        self.ln_bias = materialize_parameter(
            [embed_dim], ln_bias_attr, self._dtype, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        residual = query
        x = query
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], self.pre_ln_scale,
                             self.pre_ln_bias, self._epsilon)
        b, n = x.shape[0], x.shape[1]
        # qkv: [B,N,E] @ [E, 3*H*D] -> [B,N,3,H,D]
        qkv_w = manip.reshape(
            manip.transpose(self.qkv_weight, [3, 0, 1, 2]),
            [self.embed_dim, 3 * self.embed_dim])
        qkv = F.linear(x, qkv_w,
                       manip.reshape(self.qkv_bias, [3 * self.embed_dim]))
        qkv = manip.reshape(qkv, [b, n, 3, self.num_heads, self.head_dim])
        q = manip.squeeze(manip.slice(qkv, [2], [0], [1]), 2)
        k = manip.squeeze(manip.slice(qkv, [2], [1], [2]), 2)
        v = manip.squeeze(manip.slice(qkv, [2], [2], [3]), 2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            training=self.training)
        out = manip.reshape(out, [b, n, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], self.ln_scale,
                               self.ln_bias, self._epsilon)
        return out


class FusedFeedForward(Layer):
    """Reference: fused_transformer.py:478 over fused_feedforward_op.cu."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-05, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._d_model = d_model
        self._epsilon = epsilon
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = act_dropout_rate if act_dropout_rate \
            is not None else dropout_rate
        self._act = activation
        self.normalize_before = normalize_before
        self.linear1_weight = materialize_parameter(
            [d_model, dim_feedforward], linear1_weight_attr, self._dtype,
            default_initializer=I.XavierUniform())
        self.linear1_bias = materialize_parameter(
            [dim_feedforward], linear1_bias_attr, self._dtype, is_bias=True)
        self.linear2_weight = materialize_parameter(
            [dim_feedforward, d_model], linear2_weight_attr, self._dtype,
            default_initializer=I.XavierUniform())
        self.linear2_bias = materialize_parameter(
            [d_model], linear2_bias_attr, self._dtype, is_bias=True)
        self.ln1_scale = materialize_parameter(
            [d_model], ln1_scale_attr, self._dtype,
            default_initializer=I.Constant(1.0))
        self.ln1_bias = materialize_parameter(
            [d_model], ln1_bias_attr, self._dtype, is_bias=True)
        self.ln2_scale = materialize_parameter(
            [d_model], ln2_scale_attr, self._dtype,
            default_initializer=I.Constant(1.0))
        self.ln2_bias = materialize_parameter(
            [d_model], ln2_bias_attr, self._dtype, is_bias=True)

    def forward(self, src, cache=None):
        residual = src
        if self.normalize_before:
            src = F.layer_norm(src, [self._d_model], self.ln1_scale,
                               self.ln1_bias, self._epsilon)
        act = getattr(F, self._act)
        src = act(F.linear(src, self.linear1_weight, self.linear1_bias))
        src = F.dropout(src, self._act_dropout_rate, training=self.training)
        src = F.linear(src, self.linear2_weight, self.linear2_bias)
        src = F.dropout(src, self._dropout_rate, training=self.training)
        src = residual + src
        if not self.normalize_before:
            src = F.layer_norm(src, [self._d_model], self.ln2_scale,
                               self.ln2_bias, self._epsilon)
        return src


class FusedTransformerEncoderLayer(Layer):
    """Reference: fused_transformer.py:706."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = dropout_rate if attn_dropout_rate is None \
            else attn_dropout_rate
        act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedMultiTransformer(Layer):
    """Reference: fused_transformer.py:997 (fused_multi_transformer op) — the
    inference-serving stacked-decoder block."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, epsilon=1e-5, name=None, **unused):
        super().__init__()
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward, dropout_rate,
                activation, normalize_before=normalize_before)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, **kwargs):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=attn_mask)
        return out


class FusedLinear(Layer):
    """Linear with fused gemm epilogue (reference:
    incubate/nn/layer/fused_linear.py:19 over fused_gemm_epilogue_op.cc /
    cublasLt). TPU-first: XLA fuses the bias add (and any following
    activation) into the matmul epilogue on its own — one Linear under jit
    IS the fused op."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose_weight = transpose_weight
        # transpose_weight STORES the parameter as [out, in] and the gemm
        # reads it transposed (reference fused_linear.py semantics)
        w_shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = materialize_parameter(
            w_shape, weight_attr, self._dtype,
            default_initializer=I.XavierNormal())
        self.bias = materialize_parameter(
            [out_features], bias_attr, self._dtype, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, input):
        from ...ops import manipulation as manip
        w = manip.transpose(self.weight, [1, 0]) if self._transpose_weight \
            else self.weight
        return F.linear(input, w, self.bias)
