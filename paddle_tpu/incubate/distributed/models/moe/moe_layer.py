"""Mixture-of-Experts layer with expert parallelism over a mesh axis.

Reference analog: python/paddle/incubate/distributed/models/moe/
moe_layer.py:259 (MoELayer) — there, tokens are routed with argsort and moved
between ranks by the `global_scatter`/`global_gather` collective ops
(fluid/operators/collective/global_scatter_op.*), with per-rank dynamic token
counts exchanged first.

TPU-first redesign: GShard-style static-shape dispatch. The router builds
dispatch/combine tensors [tokens, experts, capacity]; token movement is two
einsums plus `jax.lax.all_to_all` over the expert-parallel mesh axis (the
global_scatter/global_gather analog, riding ICI), and expert FFNs are one
batched einsum over stacked weights [E, ...] — no per-expert loops, no
dynamic shapes, everything lands on the MXU.

Axis-name aware like mp_ops: inside a shard_map binding `moe_axis`, each
device owns E/ep experts and exchanges capacity buckets via all-to-all;
outside SPMD the layer computes all experts locally (and under pjit the same
einsum formulation lets XLA partition it).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .....framework.core import Tensor
from .....framework.jax_compat import axis_size
from .....nn.layer_base import Layer
from .....nn import initializer as I
from .....nn.initializer_util import materialize_parameter, ParamAttr
from .....ops._helpers import ensure_tensor, call_op_multi
from .....ops.dispatch import mark_collective
from .....distributed.mesh import current_mesh, mesh_key
from .....distributed.fleet.meta_parallel.mp_ops import in_spmd_axis
from .gate import top1_dispatch, top2_dispatch, naive_dispatch

__all__ = ["MoELayer"]

_GATES = {"switch": top1_dispatch, "gshard": top2_dispatch,
          "naive": naive_dispatch}


class MoELayer(Layer):
    """Expert-parallel mixture of FFN experts.

    Args:
        d_model: token embedding size.
        d_hidden: expert FFN hidden size.
        num_experts: total experts across the expert-parallel group.
        gate: "gshard" (top-2), "switch" (top-1), or "naive" (top-1, no aux).
        capacity_factor: per-expert buffer = cf * top_k * tokens / experts.
        moe_axis: mesh axis name carrying expert parallelism (the reference's
            moe_group; typically the data axis).
    After forward, `self.l_aux` holds the load-balance loss to add to the
    training objective (reference MoELayer exposes the same attribute).
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 capacity_factor=1.25, eval_capacity_factor=2.0,
                 moe_axis="data", weight_attr=None, group=None,
                 recompute_interval=0, name=None):
        super().__init__()
        if gate not in _GATES:
            raise ValueError(f"unknown gate {gate!r}; one of {list(_GATES)}")
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.gate_type = gate
        self.top_k = 2 if gate == "gshard" else 1
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.moe_axis = moe_axis
        self.l_aux = None

        init = I.XavierNormal()
        self.gate_weight = materialize_parameter(
            [d_model, num_experts], ParamAttr(initializer=init), "float32")
        self.w1 = materialize_parameter(
            [num_experts, d_model, d_hidden], weight_attr or
            ParamAttr(initializer=init), self._dtype)
        self.b1 = materialize_parameter([num_experts, d_hidden], None,
                                        self._dtype, is_bias=True)
        self.w2 = materialize_parameter(
            [num_experts, d_hidden, d_model], weight_attr or
            ParamAttr(initializer=init), self._dtype)
        self.b2 = materialize_parameter([num_experts, d_model], None,
                                        self._dtype, is_bias=True)

    def _capacity(self, tokens, experts):
        cf = self.capacity_factor if self.training else \
            self.eval_capacity_factor
        return max(4, int(math.ceil(cf * self.top_k * tokens / experts)))

    def forward(self, x):
        x = ensure_tensor(x)
        dispatch_fn = _GATES[self.gate_type]
        axis = self.moe_axis
        # static trace-time facts
        spmd = in_spmd_axis(axis)

        def fn(xv, wg, w1, b1, w2, b2):
            tokens = xv.reshape(-1, self.d_model)
            t = tokens.shape[0]
            e_total = wg.shape[1]
            cap = self._capacity(t, e_total)

            logits = tokens.astype(jnp.float32) @ wg.astype(jnp.float32)
            gates = jax.nn.softmax(logits, axis=-1)
            disp, combine, aux = dispatch_fn(gates, cap)
            disp = disp.astype(xv.dtype)
            combine = combine.astype(xv.dtype)

            # bucket tokens per (expert, capacity slot): [E, C, M]
            buckets = jnp.einsum("tec,tm->ecm", disp, tokens)
            if spmd:
                ep = axis_size(axis)
                e_local = w1.shape[0]
                if e_local * ep != e_total:
                    raise ValueError(
                        f"expert weights carry {e_local} local experts × "
                        f"ep={ep} but router has {e_total} experts")
                # exchange: every device sends each peer its share of
                # experts; receives [E_local, ep*C, M]
                buckets = jax.lax.all_to_all(buckets, axis, split_axis=0,
                                             concat_axis=1, tiled=True)
            h = jnp.einsum("ecm,emh->ech", buckets, w1) + b1[:, None, :]
            h = jax.nn.gelu(h)
            out = jnp.einsum("ech,ehm->ecm", h, w2) + b2[:, None, :]
            if spmd:
                out = jax.lax.all_to_all(out, axis, split_axis=1,
                                         concat_axis=0, tiled=True)
                # aux loss averaged over the expert-parallel group
                aux = jax.lax.pmean(aux, axis)
            y = jnp.einsum("tec,ecm->tm", combine, out)
            return y.reshape(xv.shape), aux.astype(jnp.float32)

        # Funnel keying: fn closes over `self` (unkeyable by the closure
        # scan), but the traced program is fully determined by the gate
        # kind, embedding size, the expert axis + mesh, and the ACTIVE
        # capacity factor — token/expert counts ride in via input shapes.
        # Stamping that identity (ops/dispatch.mark_collective) lets MoE
        # dispatch join chain fusion and the super-cycle instead of
        # poisoning every cycle as `collective_unkeyed`.
        mkey = mesh_key(current_mesh()) if spmd else None
        cf = self.capacity_factor if self.training else \
            self.eval_capacity_factor
        key = None
        if not spmd or mkey is not None:
            key = ("moe_layer", self.gate_type, self.top_k, self.d_model,
                   axis, bool(spmd), float(cf), mkey)
        mark_collective(fn, key)
        y, aux = call_op_multi(
            "moe_layer", fn,
            (x, self.gate_weight, self.w1, self.b1, self.w2, self.b2), 2)
        self.l_aux = aux
        return y
