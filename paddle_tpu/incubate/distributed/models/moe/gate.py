"""MoE router gates: top-1 (Switch) and top-2 (GShard) capacity dispatch.

Reference analog: python/paddle/incubate/distributed/models/moe/gate/
({naive,switch,gshard}_gate.py). The reference routes with argsort +
global_scatter (dynamic token counts per expert); TPU-first routing instead
builds *static-shape* dispatch/combine tensors [tokens, experts, capacity] —
the GShard formulation — so everything stays jit-able and MXU-friendly; token
overflow beyond an expert's capacity is dropped (standard GShard semantics).

All functions are pure jnp: gates [T, E] (f32 softmax probs) -> (dispatch
mask D [T, E, C] one-hot, combine weights W [T, E, C], aux load-balance
loss scalar).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["top1_dispatch", "top2_dispatch", "naive_dispatch"]


def _positions_in_expert(mask, offset=None):
    """0-based arrival position of each token within its expert's queue.
    mask: [T, E] one-hot float. Returns int32 [T, E] (valid where mask==1)."""
    pos = jnp.cumsum(mask, axis=0) - mask           # tokens before me
    if offset is not None:
        pos = pos + offset[None, :]
    return pos.astype(jnp.int32)


def _aux_loss(gates, mask1):
    """GShard/Switch load-balance loss: E * Σ_e mean_prob_e * mean_assign_e."""
    e = gates.shape[-1]
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(gates.dtype), axis=0)
    return jnp.sum(me * ce) * e


def top1_dispatch(gates, capacity):
    """Switch-Transformer routing: each token to its argmax expert."""
    t, e = gates.shape
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)
    aux = _aux_loss(gates, mask1)
    pos1 = _positions_in_expert(mask1)
    keep1 = mask1 * (pos1 < capacity).astype(gates.dtype)
    disp = keep1[..., None] * jax.nn.one_hot(pos1, capacity,
                                             dtype=gates.dtype)
    g1 = jnp.sum(gates * mask1, axis=-1)            # prob of chosen expert
    combine = g1[:, None, None] * disp
    return disp, combine, aux


def top2_dispatch(gates, capacity):
    """GShard top-2 routing with renormalized combine weights."""
    t, e = gates.shape
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)
    gates2 = gates * (1.0 - mask1)                  # mask out the winner
    idx2 = jnp.argmax(gates2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=gates.dtype)
    aux = _aux_loss(gates, mask1)

    pos1 = _positions_in_expert(mask1)
    # second choices queue behind every first choice for the same expert
    count1 = jnp.sum(mask1, axis=0)
    pos2 = _positions_in_expert(mask2, offset=count1)
    keep1 = mask1 * (pos1 < capacity).astype(gates.dtype)
    keep2 = mask2 * (pos2 < capacity).astype(gates.dtype)

    g1 = jnp.sum(gates * mask1, axis=-1)
    g2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    oh1 = keep1[..., None] * jax.nn.one_hot(pos1, capacity, dtype=gates.dtype)
    oh2 = keep2[..., None] * jax.nn.one_hot(pos2, capacity, dtype=gates.dtype)
    disp = oh1 + oh2
    combine = g1[:, None, None] * oh1 + g2[:, None, None] * oh2
    return disp, combine, aux


def naive_dispatch(gates, capacity):
    """NaiveGate: top-1 without load-balance loss (reference naive_gate.py)."""
    disp, combine, _ = top1_dispatch(gates, capacity)
    return disp, combine, jnp.zeros((), gates.dtype)
