"""Mixture-of-Experts. Reference analog:
python/paddle/incubate/distributed/models/moe/ (MoELayer + gates)."""
from .moe_layer import MoELayer  # noqa: F401
from .gate import top1_dispatch, top2_dispatch, naive_dispatch  # noqa: F401
