"""paddle.incubate.distributed equivalent (MoE model layers)."""
from . import models  # noqa: F401
