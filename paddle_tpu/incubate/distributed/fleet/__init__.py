"""incubate.distributed.fleet — recompute entry points (reference:
python/paddle/incubate/distributed/fleet/__init__.py)."""
from __future__ import annotations

__all__ = ["recompute_sequential", "recompute_hybrid"]


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Checkpoint a Sequential in `segments` chunks (reference
    incubate/distributed/fleet/recompute_sequential.py). ctx: dict with
    "segments" (default 1)."""
    from ....distributed.fleet.utils import recompute
    segments = int((ctx or {}).get("segments", 1))
    if hasattr(functions, "sublayers"):
        layers = [l for l in functions] if hasattr(functions, "__iter__") \
            else list(functions.sublayers(include_self=False))
    else:
        layers = list(functions)
    def run_layers(chunk, *xs):
        # first layer receives the args as given; later layers chain the
        # (single or tuple) output exactly like nn.Sequential
        out = chunk[0](*xs)
        for l in chunk[1:]:
            out = l(*out) if isinstance(out, tuple) else l(out)
        return out

    if segments <= 1 or len(layers) <= 1:
        return recompute(lambda *xs: run_layers(layers, *xs), *args,
                         **kwargs)
    per = max(len(layers) // segments, 1)
    out = args
    for s in range(0, len(layers), per):
        chunk = layers[s:s + per]
        cur = out if isinstance(out, tuple) else (out,)
        out = recompute(lambda *xs, c=chunk: run_layers(c, *xs), *cur,
                        **kwargs)
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Recompute in hybrid-parallel context (reference recompute_hybrid.py:
    mp-aware RNG + optional offload). The mesh-global RNG tracker already
    keys dropout per (step, stage), so this reduces to recompute; the
    "offload" knob is accepted (XLA remat already avoids storing)."""
    from ....distributed.fleet.utils import recompute
    kwargs.pop("offload", None)
    return recompute(function, *args, **kwargs)
