"""Automatic epoch-level checkpoint/resume.

Reference analog: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(train_epoch_range :642, checkpoint checker :72) — epoch bookkeeping with a
run id so a restarted job resumes at the first unfinished epoch.

TPU-native simplification: state lives in a local/NFS directory (the
reference used HDFS); model/optimizer snapshots go through paddle.save or
distributed.checkpoint.save_state_dict.

Crash safety (PR 5): `EpochRange.save()` snapshots model / optimizer /
GradScaler / RNG state atomically (framework.io.save: tmp + `os.replace` +
CRC trailer) with rolling retention, and `restore()` brings all of it back —
including the optimizer step counter, so LR schedules and whole-step fusion
recording (ops/step_fusion.py) continue exactly where the killed run
stopped. A checkpoint that fails its CRC (`CheckpointCorruptError`) is
skipped in favor of the next retained one instead of poisoning the resume.
The chaos harness (tools/chaos.py, kill scenario) proves the end-to-end
property: kill -9 mid-epoch, resume, and the final parameters match an
uninterrupted run bit-for-bit.

PR 7 extends the same machinery from the training loop to the serving
loop: `ServeCheckpointer` snapshots an LLMEngine's request/scheduler
state every N engine steps, so a killed server restarts and finishes
every in-flight stream byte-identically (tools/chaos.py `serve_kill`).
"""
from __future__ import annotations

import json
import os
import re
import shutil
import time

__all__ = ["train_epoch_range", "EpochRange", "StepCheckpointer",
           "ServeCheckpointer"]


def _state_of(model):
    """State payload for `model`: a Layer-like (state_dict()) or a plain
    mapping of name -> Tensor/Parameter (saved as-is)."""
    if model is None:
        return None
    if hasattr(model, "state_dict"):
        return model.state_dict()
    return dict(model)


def _apply_model_state(model, state):
    if model is None or state is None:
        return
    if hasattr(model, "set_state_dict"):
        model.set_state_dict(state)
        return
    # mapping form: copy loaded buffers into the CALLER's tensors in place
    for name, t in model.items():
        v = state[name]
        t._value = v._value if hasattr(v, "_value") else v


def _snapshot_payload(model, optimizer, scaler, extra):
    """One resumable training snapshot: model + optimizer (accumulators,
    step counter, LR schedule) + GradScaler + the global RNG stream —
    shared by EpochRange.save and StepCheckpointer.save so epoch- and
    step-granular checkpoints stay byte-compatible."""
    from ..framework import random as _random
    return {
        "model": _state_of(model),
        "optimizer": None if optimizer is None else optimizer.state_dict(),
        "scaler": None if scaler is None else scaler.state_dict(),
        "rng": _random.rng_checkpoint_state(),
        "extra": extra,
    }


def _apply_payload(payload, model, optimizer, scaler):
    from ..framework import random as _random
    _apply_model_state(model, payload.get("model"))
    if optimizer is not None and payload.get("optimizer") is not None:
        optimizer.set_state_dict(payload["optimizer"])
    if scaler is not None and payload.get("scaler") is not None:
        scaler.load_state_dict(payload["scaler"])
    if payload.get("rng") is not None:
        _random.set_rng_checkpoint_state(payload["rng"])


class EpochRange:
    """Iterate epochs [0, max_epoch_num) resuming after the last completed
    one.

    Usage:
        er = train_epoch_range(10, save_dir=".auto_ckpt")
        er.restore(model=model, optimizer=opt, scaler=scaler)
        for epoch in er:
            train_one_epoch(...)
            er.save(epoch, model=model, optimizer=opt, scaler=scaler)

    `save()` writes one atomic, CRC-protected snapshot per epoch (keeping
    the newest `max_checkpoints`), and `restore()` loads the newest intact
    one — optimizer step counter, LR-schedule state, loss-scale
    growth-tracker, and RNG stream included.
    """

    CKPT_FILE = "state.pdckpt"

    def __init__(self, max_epoch_num, save_dir=None, run_id=None,
                 save_checkpoint_inter=1, max_checkpoints=3):
        self.max_epoch_num = max_epoch_num
        self.save_checkpoint_inter = max(1, int(save_checkpoint_inter or 1))
        self.max_checkpoints = max(1, int(max_checkpoints or 1))
        self.save_dir = save_dir or os.environ.get(
            "PADDLE_TPU_AUTO_CKPT_DIR", ".auto_checkpoint")
        self.run_id = run_id or os.environ.get("PADDLE_JOB_ID", "default")
        self._meta_path = os.path.join(self.save_dir,
                                       f"range_{self.run_id}.json")
        self._completed = -1
        if os.path.exists(self._meta_path):
            try:
                with open(self._meta_path) as f:
                    meta = json.load(f)
                if meta.get("max_epoch_num") == max_epoch_num:
                    self._completed = int(meta.get("completed_epoch", -1))
            except (json.JSONDecodeError, OSError):
                pass

    @property
    def restored_from(self):
        """Index of the last completed epoch (-1 = fresh run)."""
        return self._completed

    def _mark(self, epoch):
        os.makedirs(self.save_dir, exist_ok=True)
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"run_id": self.run_id,
                       "max_epoch_num": self.max_epoch_num,
                       "completed_epoch": epoch,
                       "timestamp": time.time()}, f)
        os.replace(tmp, self._meta_path)

    def __iter__(self):
        for epoch in range(self._completed + 1, self.max_epoch_num):
            yield epoch
            if epoch > self._completed:
                self._completed = epoch
            # persist progress every save_checkpoint_inter epochs (+ final)
            if ((epoch + 1) % self.save_checkpoint_inter == 0
                    or epoch == self.max_epoch_num - 1):
                self._mark(self._completed)

    def checkpoint_path(self, epoch=None):
        """Directory for this run's (epoch) artifacts."""
        e = self._completed + 1 if epoch is None else epoch
        return os.path.join(self.save_dir, self.run_id, f"epoch_{e}")

    # -- crash-safe state snapshots -----------------------------------------
    def save(self, epoch, model=None, optimizer=None, scaler=None,
             extra=None):
        """Atomic end-of-epoch snapshot: model (Layer or name->Tensor
        mapping), optimizer (accumulators + step counter + LR schedule),
        GradScaler (loss scale + growth tracker), the global RNG stream,
        and any JSON/pickle-able `extra`. Marks `epoch` completed and
        prunes checkpoints beyond the newest `max_checkpoints`. Returns
        the checkpoint directory."""
        from ..framework import io as _io
        payload = _snapshot_payload(model, optimizer, scaler, extra)
        payload["epoch"] = int(epoch)
        d = self.checkpoint_path(epoch)
        _io.save(payload, os.path.join(d, self.CKPT_FILE))
        if epoch > self._completed:
            self._completed = int(epoch)
        self._mark(self._completed)
        self._prune()
        return d

    def _retained_epochs(self):
        base = os.path.join(self.save_dir, self.run_id)
        if not os.path.isdir(base):
            return []
        eps = []
        for nm in os.listdir(base):
            m = re.fullmatch(r"epoch_(\d+)", nm)
            if m:
                eps.append(int(m.group(1)))
        return sorted(eps)

    def _prune(self):
        """Rolling retention: keep the newest `max_checkpoints` completed
        epoch snapshots, delete the rest."""
        eps = [e for e in self._retained_epochs() if e <= self._completed]
        for e in eps[:-self.max_checkpoints]:
            shutil.rmtree(self.checkpoint_path(e), ignore_errors=True)

    def restore(self, model=None, optimizer=None, scaler=None):
        """Load the newest intact snapshot at or below the last completed
        epoch into the given objects (each optional) and restore the RNG
        stream. A corrupt snapshot (torn write on a crashed fs, CRC
        mismatch) falls back to the next retained one. Returns the saved
        `extra` payload, or None when nothing was restored."""
        from ..framework import io as _io
        if self._completed < 0:
            return None
        candidates = [e for e in self._retained_epochs()
                      if e <= self._completed]
        corrupt = []
        for e in reversed(candidates):
            path = os.path.join(self.checkpoint_path(e), self.CKPT_FILE)
            if not os.path.exists(path):
                continue
            try:
                payload = _io.load(path)
            except _io.CheckpointCorruptError:
                corrupt.append(path)
                continue
            _apply_payload(payload, model, optimizer, scaler)
            if e != self._completed:
                # resumed from an OLDER epoch (newer snapshot was corrupt):
                # re-run the epochs after it
                self._completed = e
                self._mark(e)
            return payload.get("extra")
        if corrupt:
            # snapshots existed but NONE survived the integrity check:
            # silently training epochs _completed+1.. on fresh-initialized
            # state would be exactly the garbage-resume this machinery
            # exists to prevent — make the operator decide
            raise _io.CheckpointCorruptError(
                "every retained checkpoint failed its integrity check "
                f"({', '.join(corrupt)}); refusing to resume epoch "
                f"{self._completed + 1} on uninitialized state — delete "
                "the marker file to restart from scratch")
        return None


class _RollingStore:
    """Shared skeleton of the numbered rolling-retention checkpoint
    stores: atomic CRC snapshots in `<save_dir>/<run_id>_<suffix>/
    <prefix>_<n>/`, newest `max_checkpoints` kept, newest-first restore
    scan that skips corrupt snapshots and REFUSES when none survives.
    `StepCheckpointer` (training state) and `ServeCheckpointer`
    (serving state) differ only in what the payload is — the retention
    and integrity machinery must not be able to diverge between them.
    """

    CKPT_FILE = EpochRange.CKPT_FILE
    _DIR_SUFFIX = ""     # subclass: directory name suffix
    _ITEM_PREFIX = ""    # subclass: per-snapshot directory prefix
    _REFUSAL = ""        # subclass: all-corrupt refusal message tail

    def __init__(self, save_dir, save_every_n_steps, run_id,
                 max_checkpoints):
        self.save_dir = save_dir
        self.save_every_n_steps = max(1, int(save_every_n_steps))
        self.max_checkpoints = max(1, int(max_checkpoints or 1))
        self.run_id = run_id or os.environ.get("PADDLE_JOB_ID", "default")

    def _base(self):
        return os.path.join(self.save_dir,
                            f"{self.run_id}_{self._DIR_SUFFIX}")

    def checkpoint_path(self, step):
        return os.path.join(self._base(), f"{self._ITEM_PREFIX}_{step}")

    def _retained(self):
        base = self._base()
        if not os.path.isdir(base):
            return []
        out = []
        for nm in os.listdir(base):
            m = re.fullmatch(rf"{self._ITEM_PREFIX}_(\d+)", nm)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def _on_grid(self, step):
        return int(step) % self.save_every_n_steps == 0

    def _save_numbered(self, step, payload):
        """Atomic snapshot at `step` + prune beyond the newest
        `max_checkpoints`. Returns the checkpoint directory."""
        from ..framework import io as _io
        d = self.checkpoint_path(int(step))
        _io.save(payload, os.path.join(d, self.CKPT_FILE))
        for s in self._retained()[:-self.max_checkpoints]:
            shutil.rmtree(self.checkpoint_path(s), ignore_errors=True)
        return d

    def _restore_scan(self):
        """(step, payload) of the newest intact snapshot, or None.
        Corrupt snapshots fall back to older ones; when snapshots exist
        but NONE survives the integrity check, raise instead of silently
        resuming on nothing."""
        from ..framework import io as _io
        corrupt = []
        for s in reversed(self._retained()):
            path = os.path.join(self.checkpoint_path(s), self.CKPT_FILE)
            if not os.path.exists(path):
                continue
            try:
                return s, _io.load(path)
            except _io.CheckpointCorruptError:
                corrupt.append(path)
        if corrupt:
            raise _io.CheckpointCorruptError(
                f"every retained {self._ITEM_PREFIX} checkpoint failed "
                f"its integrity check ({', '.join(corrupt)}); "
                f"{self._REFUSAL}")
        return None


class StepCheckpointer(_RollingStore):
    """Step-granular `save_every_n_steps` checkpoints on the same atomic,
    CRC-verified, rolling-retention machinery as `EpochRange` — for runs
    where an epoch is hours long and preemption (spot TPU reclaims,
    serving-engine co-tenancy, the multi-host runs of ROADMAP item 1)
    cannot afford to lose one.

    Usage::

        ck = StepCheckpointer(".ckpt", save_every_n_steps=200)
        start = ck.restore(model=model, optimizer=opt, scaler=scaler)
        for step, batch in enumerate(loader, start=start + 1):
            train_step(batch)
            ck.tick(step, model=model, optimizer=opt, scaler=scaler)

    `tick(step)` saves only on every n-th step (cheap no-op otherwise);
    `restore()` loads the newest intact snapshot — optimizer step
    counter, LR schedule, loss-scale growth tracker, and RNG stream
    included — skipping corrupt files, and returns the step it resumed
    at (-1 for a fresh run). Like EpochRange, it REFUSES (raises) when
    snapshots exist but none survives the integrity check.
    """

    _DIR_SUFFIX = "steps"
    _ITEM_PREFIX = "step"
    _REFUSAL = ("refusing to resume on uninitialized state — delete the "
                "step_* directories to restart from scratch")

    def __init__(self, save_dir, save_every_n_steps=100, run_id=None,
                 max_checkpoints=3):
        super().__init__(save_dir, save_every_n_steps, run_id,
                         max_checkpoints)
        self.last_extra = None

    # kept under its historical name (the rolling-retention tests and
    # downstream tooling read it)
    def _retained_steps(self):
        return self._retained()

    def tick(self, step, model=None, optimizer=None, scaler=None,
             extra=None):
        """Per-step hook: saves when `step` lands on the
        save_every_n_steps grid, else returns None without touching the
        filesystem."""
        if not self._on_grid(step):
            return None
        return self.save(step, model=model, optimizer=optimizer,
                         scaler=scaler, extra=extra)

    def save(self, step, model=None, optimizer=None, scaler=None,
             extra=None):
        """Unconditional atomic snapshot at `step`; prunes beyond the
        newest `max_checkpoints`. Returns the checkpoint directory."""
        payload = _snapshot_payload(model, optimizer, scaler, extra)
        payload["step"] = int(step)
        return self._save_numbered(step, payload)

    def restore(self, model=None, optimizer=None, scaler=None):
        """Load the newest intact step snapshot into the given objects;
        corrupt snapshots fall back to older ones. Returns the resumed
        step (-1 when no snapshot exists); the saved `extra` lands in
        `self.last_extra`."""
        found = self._restore_scan()
        if found is None:
            return -1
        s, payload = found
        _apply_payload(payload, model, optimizer, scaler)
        self.last_extra = payload.get("extra")
        return int(payload.get("step", s))


class ServeCheckpointer(_RollingStore):
    """Crash-resumable SERVING state on the StepCheckpointer's atomic,
    CRC-verified, rolling-retention machinery (PR 7).

    The payload is the engine's `state_payload()` — prompts, emitted
    tokens, arrival order, remaining TTLs; never the KV pool, which
    re-prefills token-identically on resume — so a snapshot is a few KB
    of host data and `tick()` every engine step is affordable. A kill-9'd
    server restarts, `restore()`s the newest intact snapshot, feeds it to
    `engine.restore_state()`, and every in-flight stream finishes
    byte-identically (tools/chaos.py `serve_kill` proves it).

    Usage::

        ck = ServeCheckpointer(".serve_ckpt", save_every_n_steps=1)
        engine.restore_state(ck.restore())
        n = 0
        while engine.step():
            n += 1
            ck.tick(n, engine.state_payload())
    """

    _DIR_SUFFIX = "serve"
    _ITEM_PREFIX = "serve"
    _REFUSAL = ("refusing to restart with silently dropped in-flight "
                "requests — delete the serve_* directories to start "
                "empty")

    def __init__(self, save_dir, save_every_n_steps=1, run_id=None,
                 max_checkpoints=3):
        super().__init__(save_dir, save_every_n_steps, run_id,
                         max_checkpoints)

    def tick(self, step, payload):
        """Save `payload` when `step` lands on the grid (else a cheap
        no-op). Returns the checkpoint directory or None."""
        if not self._on_grid(step):
            return None
        return self.save(step, payload)

    def save(self, step, payload):
        """Unconditional atomic snapshot of the serving payload at
        `step`; prunes beyond the newest `max_checkpoints`."""
        return self._save_numbered(step, {"step": int(step),
                                          "serve": payload})

    def restore(self):
        """The newest intact serving payload (for
        `engine.restore_state()`), or None for a fresh start."""
        found = self._restore_scan()
        return None if found is None else found[1].get("serve")


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None,
                      save_dir=None, run_id=None, max_checkpoints=3):
    return EpochRange(max_epoch_num, save_dir=save_dir, run_id=run_id,
                      save_checkpoint_inter=save_checkpoint_inter,
                      max_checkpoints=max_checkpoints)
