"""Automatic epoch-level checkpoint/resume.

Reference analog: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py
(train_epoch_range :642, checkpoint checker :72) — epoch bookkeeping with a
run id so a restarted job resumes at the first unfinished epoch.

TPU-native simplification: state lives in a local/NFS directory (the
reference used HDFS); model/optimizer snapshots go through paddle.save or
distributed.checkpoint.save_state_dict.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["train_epoch_range", "EpochRange"]


class EpochRange:
    """Iterate epochs [0, max_epoch) resuming after the last completed one.

    Usage:
        for epoch in train_epoch_range(10, save_dir=".auto_ckpt"):
            train_one_epoch(...)
    Snapshot model/optimizer state into `checkpoint_path(epoch)` inside the
    loop (paddle.save or distributed.checkpoint.save_state_dict).
    """

    def __init__(self, max_epoch_num, save_dir=None, run_id=None,
                 save_checkpoint_inter=1):
        self.max_epoch_num = max_epoch_num
        self.save_checkpoint_inter = max(1, int(save_checkpoint_inter or 1))
        self.save_dir = save_dir or os.environ.get(
            "PADDLE_TPU_AUTO_CKPT_DIR", ".auto_checkpoint")
        self.run_id = run_id or os.environ.get("PADDLE_JOB_ID", "default")
        self._meta_path = os.path.join(self.save_dir,
                                       f"range_{self.run_id}.json")
        self._completed = -1
        if os.path.exists(self._meta_path):
            try:
                with open(self._meta_path) as f:
                    meta = json.load(f)
                if meta.get("max_epoch_num") == max_epoch_num:
                    self._completed = int(meta.get("completed_epoch", -1))
            except (json.JSONDecodeError, OSError):
                pass

    @property
    def restored_from(self):
        """Index of the last completed epoch (-1 = fresh run)."""
        return self._completed

    def _mark(self, epoch):
        os.makedirs(self.save_dir, exist_ok=True)
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"run_id": self.run_id,
                       "max_epoch_num": self.max_epoch_num,
                       "completed_epoch": epoch,
                       "timestamp": time.time()}, f)
        os.replace(tmp, self._meta_path)

    def __iter__(self):
        for epoch in range(self._completed + 1, self.max_epoch_num):
            yield epoch
            self._completed = epoch
            # persist progress every save_checkpoint_inter epochs (+ final)
            if ((epoch + 1) % self.save_checkpoint_inter == 0
                    or epoch == self.max_epoch_num - 1):
                self._mark(epoch)

    def checkpoint_path(self, epoch=None):
        """Directory for this run's (epoch) artifacts."""
        e = self._completed + 1 if epoch is None else epoch
        return os.path.join(self.save_dir, self.run_id, f"epoch_{e}")


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None,
                      save_dir=None, run_id=None):
    return EpochRange(max_epoch_num, save_dir=save_dir, run_id=run_id,
                      save_checkpoint_inter=save_checkpoint_inter)
