"""ASP — automatic structured (n:m) sparsity.

Reference analog: python/paddle/incubate/asp/ (utils.py mask calculators
create_mask/check_sparsity, asp.py prune_model/decorate — the reference
targets Ampere 2:4 sparse tensor cores).

TPU note: the MXU has no sparse mode, so n:m sparsity here is a model
compression / regularization feature (masked weights stay dense in compute),
with identical mask semantics + the optimizer decoration that re-applies
masks after each step so pruned weights stay zero through training.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor

__all__ = ["calculate_density", "create_mask", "check_sparsity",
           "prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers"]

_EXCLUDED = set()
# masks are stored on the pruned model itself (model._asp_masks) so two
# models with identical parameter names cannot cross-contaminate


def calculate_density(x):
    v = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float((v != 0).sum()) / max(v.size, 1)


def _mask_1d(vec, n, m):
    """Keep the n largest-|.| of every m consecutive values."""
    pad = (-len(vec)) % m
    vp = np.pad(vec, (0, pad))
    groups = np.abs(vp.reshape(-1, m))
    keep = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask, keep, True, axis=1)
    return mask.reshape(-1)[:len(vec)]


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """n:m mask with the reference's group-along-rows convention
    (asp/utils.py create_mask)."""
    v = np.asarray(tensor._value if isinstance(tensor, Tensor) else tensor)
    shape = v.shape
    flat = v.reshape(shape[0], -1) if v.ndim > 1 else v.reshape(1, -1)
    mask = np.stack([_mask_1d(row, n, m) for row in flat])
    return mask.reshape(shape)


def check_sparsity(tensor, func_name="check_mask_1d", n=2, m=4):
    """Row-wise n:m check matching create_mask's per-row grouping (groups
    never straddle row boundaries)."""
    v = np.asarray(tensor._value if isinstance(tensor, Tensor) else tensor)
    rows = v.reshape(v.shape[0], -1) if v.ndim > 1 else v.reshape(1, -1)
    pad = (-rows.shape[1]) % m
    vp = np.pad(rows, ((0, 0), (0, pad))).reshape(rows.shape[0], -1, m)
    return bool((np.count_nonzero(vp, axis=2) <= n).all())


def set_excluded_layers(param_names, main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def _prunable(name, param):
    if name in _EXCLUDED or param.stop_gradient:
        return False
    v = param._value
    return v.ndim >= 2 and min(v.shape) >= 4 and "bias" not in name


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every prunable weight in place; remember masks so
    `decorate`d optimizers keep them enforced."""
    import jax.numpy as jnp
    pruned = {}
    for name, param in model.named_parameters():
        if not _prunable(name, param):
            continue
        mask = create_mask(param, func_name=mask_algo, n=n, m=m)
        param._value = param._value * jnp.asarray(mask, param._value.dtype)
        pruned[name] = mask
    if with_mask:
        model._asp_masks = pruned
    return pruned


class _ASPOptimizerWrapper:
    """Reference analog: asp.decorate -> OptimizerWithSparsityGuarantee.
    After every step, re-zero the pruned weights."""

    def __init__(self, optimizer, model):
        self._opt = optimizer
        self._model = model

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def _reapply_masks(self):
        import jax.numpy as jnp
        if self._model is None:
            return
        masks = getattr(self._model, "_asp_masks", None) or {}
        for name, param in self._model.named_parameters():
            mask = masks.get(name)
            if mask is not None:
                param._value = param._value * jnp.asarray(
                    mask, param._value.dtype)

    def step(self):
        self._opt.step()
        self._reapply_masks()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        out = self._opt.minimize(loss, startup_program=startup_program,
                                 parameters=parameters,
                                 no_grad_set=no_grad_set)
        self._reapply_masks()
        return out


def decorate(optimizer, model=None):
    return _ASPOptimizerWrapper(optimizer, model)
