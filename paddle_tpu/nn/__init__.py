"""paddle.nn equivalent surface."""
from .layer_base import Layer  # noqa: F401
from .initializer_util import ParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401
from . import utils  # noqa: F401

from .layer.container import Sequential, LayerList, LayerDict, ParameterList  # noqa: F401
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.rnn import RNNCellBase  # noqa: F401
from .layer.decode import BeamSearchDecoder, dynamic_decode  # noqa: F401

from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401

from ..framework.core import Parameter  # noqa: F401
