"""Gradient clipping. Reference analog: python/paddle/fluid/clip.py
(ClipGradByGlobalNorm etc.), applied by the optimizer before update."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm", "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._value.astype(jnp.float32) * scale)
                                  .astype(g._value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _global_norm(self, grads):
        sq = [jnp.sum(jnp.square(g._value.astype(jnp.float32))) for g in grads]
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        return jnp.sqrt(total)

    def __call__(self, params_grads):
        clippable = [(p, g) for p, g in params_grads
                     if g is not None and getattr(p, "need_clip", True)]
        if not clippable:
            return params_grads
        gnorm = self._global_norm([g for _, g in clippable])
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._value.astype(jnp.float32) * scale)
                                      .astype(g._value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        norms = [jnp.max(jnp.abs(g._value)) for g in grads]
        total = norms[0]
        for n in norms[1:]:
            total = jnp.maximum(total, n)
    else:
        sq = [jnp.sum(jnp.power(jnp.abs(g._value.astype(jnp.float32)),
                                norm_type)) for g in grads]
        acc = sq[0]
        for s in sq[1:]:
            acc = acc + s
        total = jnp.power(acc, 1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad._value = (p.grad._value.astype(jnp.float32) * scale) \
                .astype(p.grad._value.dtype)
    return Tensor(total)
