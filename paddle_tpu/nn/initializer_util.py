"""ParamAttr + parameter materialization.

Reference analog: python/paddle/fluid/param_attr.py (ParamAttr) and
LayerHelper.create_parameter.
"""
from __future__ import annotations

from ..framework.core import Parameter
from ..framework.dtype import to_jax_dtype
from . import initializer as I

__all__ = ["ParamAttr", "materialize_parameter"]


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, I.Initializer):
            return ParamAttr(initializer=arg)
        if arg is False:
            return False
        raise TypeError(f"Unsupported param attr: {arg!r}")


def materialize_parameter(shape, attr=None, dtype="float32", is_bias=False,
                          default_initializer=None):
    """Create an initialized Parameter (returns None if attr is False)."""
    if attr is False:
        return None
    attr = ParamAttr._to_attr(attr)
    # precedence: explicit attr > set_global_initializer (it overrides the
    # LAYER's default too — reference semantics: applies wherever the user
    # did not pass an initializer) > layer default > built-in
    init = attr.initializer or I._global_initializer(is_bias) \
        or default_initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    shape = [int(s) for s in shape]
    value = init(tuple(shape), to_jax_dtype(dtype))
    p = Parameter(value, name=attr.name, trainable=attr.trainable)
    p.optimize_attr["learning_rate"] = attr.learning_rate
    p.regularizer = attr.regularizer
    p.need_clip = attr.need_clip
    return p
