"""paddle.nn.functional equivalent."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import scaled_dot_product_attention, sparse_attention  # noqa: F401
from .vision import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403

from ...ops.manipulation import pad, diag_embed  # noqa: F401  (paddle exposes F.pad)
