"""Pooling functionals over lax.reduce_window.

Reference analog: python/paddle/nn/functional/pooling.py over phi pool kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, unary, call_op
from ...ops.registry import register_op

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d"]


def _norm(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _pool(x, kernel, stride, padding, n, reducer, init, is_avg,
          exclusive=True, ceil_mode=False, channel_last=False, op_name="pool"):
    x = ensure_tensor(x)
    kernel = _norm(kernel, n)
    stride = _norm(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        p = _norm(padding, n)
        pads = [(pi, pi) for pi in p]

    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        base_pad = [(0, 0)] + (pads or [(0, 0)] * n) + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        base_pad = [(0, 0), (0, 0)] + (pads or [(0, 0)] * n)

    def fn(v):
        if pad_mode == "SAME":
            padding_cfg = "SAME"
        elif pad_mode == "VALID":
            padding_cfg = "VALID"
        else:
            padding_cfg = base_pad
            if ceil_mode:
                padding_cfg = list(base_pad)
                off = 1 if channel_last else 2
                for i in range(n):
                    dim = v.shape[off + i]
                    lo, hi = padding_cfg[off + i]
                    out_f = (dim + lo + hi - kernel[i]) / stride[i] + 1
                    out_c = int(np.ceil(out_f))
                    need = (out_c - 1) * stride[i] + kernel[i] - (dim + lo + hi)
                    padding_cfg[off + i] = (lo, hi + max(need, 0))
        # init must be a CONCRETE scalar (not a traced jnp array) so jax
        # recognizes the monoid and keeps reduce_window differentiable
        # under jit(grad(...))
        zero = np.zeros((), v.dtype)[()]
        if is_avg:
            if exclusive and (pads or ceil_mode):
                ones = jnp.ones_like(v)
                s = lax.reduce_window(v, zero, lax.add,
                                      window, strides, padding_cfg)
                c = lax.reduce_window(ones, zero, lax.add,
                                      window, strides, padding_cfg)
                return s / c
            s = lax.reduce_window(v, zero, lax.add,
                                  window, strides, padding_cfg)
            return s / np.prod(kernel)
        return lax.reduce_window(v, np.asarray(init, v.dtype)[()], reducer,
                                 window, strides, padding_cfg)
    return unary(op_name, fn, x)


@register_op("max_pool2d", "pooling", ref="phi/kernels/pool_kernel.h")
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, lax.max, -np.inf, False,
                ceil_mode=ceil_mode, channel_last=data_format == "NHWC",
                op_name="max_pool2d")
    if return_mask:
        mask = _max_pool_mask(ensure_tensor(x), kernel_size, stride, padding, 2,
                              data_format == "NHWC")
        return out, mask
    return out


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    out = _pool(x, kernel_size, stride, padding, 1, lax.max, -np.inf, False,
                ceil_mode=ceil_mode, op_name="max_pool1d")
    if return_mask:
        mask = _max_pool_mask(ensure_tensor(x), kernel_size, stride, padding, 1,
                              False)
        return out, mask
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, lax.max, -np.inf, False,
                ceil_mode=ceil_mode, channel_last=data_format == "NDHWC",
                op_name="max_pool3d")
    if return_mask:
        mask = _max_pool_mask(ensure_tensor(x), kernel_size, stride, padding, 3,
                              data_format == "NDHWC")
        return out, mask
    return out


def _max_pool_mask(x, kernel, stride, padding, n, channel_last):
    """Indices of max elements (flattened per spatial window input)."""
    kernel_t = _norm(kernel, n)
    stride_t = _norm(stride if stride is not None else kernel, n)
    p = _norm(padding if not isinstance(padding, str) else 0, n)
    v = x._value
    spatial_off = 1 if channel_last else 2
    spatial = v.shape[spatial_off:spatial_off + n]
    flat_idx = jnp.arange(int(np.prod(spatial))).reshape(spatial)
    shape = [1] * v.ndim
    for i in range(n):
        shape[spatial_off + i] = spatial[i]
    idx_map = jnp.broadcast_to(flat_idx.reshape(shape), v.shape)

    if channel_last:
        window = (1,) + kernel_t + (1,)
        strides = (1,) + stride_t + (1,)
        pads = [(0, 0)] + [(pi, pi) for pi in p] + [(0, 0)]
    else:
        window = (1, 1) + kernel_t
        strides = (1, 1) + stride_t
        pads = [(0, 0), (0, 0)] + [(pi, pi) for pi in p]

    def select(acc, cur):
        acc_v, acc_i = acc
        cur_v, cur_i = cur
        take_cur = cur_v > acc_v
        return (jnp.where(take_cur, cur_v, acc_v),
                jnp.where(take_cur, cur_i, acc_i))

    _, mask = lax.reduce_window(
        (v, idx_map.astype(jnp.int32)),
        (jnp.asarray(-np.inf, v.dtype), jnp.asarray(0, jnp.int32)),
        select, window, strides, pads)
    return Tensor(mask.astype(jnp.int64))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, lax.add, 0, True,
                 exclusive=exclusive, ceil_mode=ceil_mode,
                 op_name="avg_pool1d")


@register_op("avg_pool2d", "pooling")
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    if divisor_override:
        x = ensure_tensor(x)
        kernel_t = _norm(kernel_size, 2)
        out = _pool(x, kernel_size, stride, padding, 2, lax.add, 0, False,
                    channel_last=data_format == "NHWC", op_name="avg_pool2d")
        return out * (1.0 / divisor_override)
    return _pool(x, kernel_size, stride, padding, 2, lax.add, 0, True,
                 exclusive=exclusive, ceil_mode=ceil_mode,
                 channel_last=data_format == "NHWC", op_name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, lax.add, 0, True,
                 exclusive=exclusive, ceil_mode=ceil_mode,
                 channel_last=data_format == "NDHWC", op_name="avg_pool3d")


def _adaptive_pool(x, output_size, n, is_avg, channel_last, op_name,
                   return_mask=False):
    x = ensure_tensor(x)
    if isinstance(output_size, int):
        output_size = (output_size,) * n
    output_size = tuple(int(o) if o is not None else None for o in output_size)
    spatial_off = 1 if channel_last else 2
    in_spatial = x._value.shape[spatial_off:spatial_off + n]
    output_size = tuple(o if o is not None else s
                        for o, s in zip(output_size, in_spatial))

    def fn(v):
        out = v
        for i in range(n):
            ax = spatial_off + i
            in_n, out_n = in_spatial[i], output_size[i]
            # adaptive windows: start = floor(j*in/out), end = ceil((j+1)*in/out)
            starts = [int(np.floor(j * in_n / out_n)) for j in range(out_n)]
            ends = [int(np.ceil((j + 1) * in_n / out_n)) for j in range(out_n)]
            slices = []
            for s, e in zip(starts, ends):
                seg = lax.slice_in_dim(out, s, e, axis=ax)
                red = jnp.mean(seg, axis=ax, keepdims=True) if is_avg \
                    else jnp.max(seg, axis=ax, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=ax)
        return out
    out = unary(op_name, fn, x)
    if return_mask:
        # compute indices by brute comparison per output cell
        mask = _adaptive_max_mask(x, output_size, n, channel_last)
        return out, mask
    return out


def _adaptive_max_mask(x, output_size, n, channel_last):
    v = np.asarray(x._value)
    spatial_off = 1 if channel_last else 2
    in_spatial = v.shape[spatial_off:spatial_off + n]
    flat = np.arange(int(np.prod(in_spatial))).reshape(in_spatial)
    out_idx = np.zeros(v.shape[:spatial_off] + tuple(output_size), np.int64)
    # iterate output cells (host-side; mask path is a rarely-hot debug feature)
    from itertools import product
    for cell in product(*[range(o) for o in output_size]):
        sl = tuple(
            slice(int(np.floor(c * i / o)), int(np.ceil((c + 1) * i / o)))
            for c, i, o in zip(cell, in_spatial, output_size))
        window = v[(Ellipsis,) + sl] if channel_last else \
            v[(slice(None), slice(None)) + sl]
        w2 = window.reshape(window.shape[:spatial_off] + (-1,))
        am = w2.argmax(axis=-1)
        widx = flat[sl].reshape(-1)
        out_idx[(slice(None), slice(None)) + cell] = widx[am]
    return Tensor(jnp.asarray(out_idx))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, True, False,
                          "adaptive_avg_pool1d")


@register_op("adaptive_avg_pool2d", "pooling")
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, True, data_format == "NHWC",
                          "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, True, data_format == "NDHWC",
                          "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, False, False,
                          "adaptive_max_pool1d", return_mask)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, False, False,
                          "adaptive_max_pool2d", return_mask)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, False, False,
                          "adaptive_max_pool3d", return_mask)


def _max_unpool(x, indices, kernel_size, stride, padding, n, output_size,
                channel_last, op_name):
    """Scatter pooled values back to the pre-pool positions recorded in
    `indices` (the flat-spatial mask from max_poolNd(return_mask=True)).
    Reference analog: phi/kernels/unpool_kernel.h."""
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)
    kernel_t = _norm(kernel_size, n)
    stride_t = _norm(stride if stride is not None else kernel_size, n)
    p = _norm(padding, n)
    spatial_off = 1 if channel_last else 2
    in_spatial = x._value.shape[spatial_off:spatial_off + n]
    if output_size is None:
        out_spatial = tuple(
            (in_spatial[i] - 1) * stride_t[i] - 2 * p[i] + kernel_t[i]
            for i in range(n))
    else:
        out_spatial = tuple(int(s) for s in tuple(output_size)[-n:])
    if channel_last:
        raise NotImplementedError(f"{op_name}: NHWC unpool not supported")
    N, C = x._value.shape[0], x._value.shape[1]
    P = int(np.prod(out_spatial))

    def fn(v, idx):
        flat_v = v.reshape(N * C, -1)
        flat_i = idx.reshape(N * C, -1).astype(jnp.int32)
        out = jnp.zeros((N * C, P), v.dtype)
        rows = jnp.arange(N * C)[:, None]
        out = out.at[rows, flat_i].set(flat_v)
        return out.reshape((N, C) + out_spatial)

    return call_op(op_name, fn, (x, indices))


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 1,
                       output_size, data_format == "NLC", "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 2,
                       output_size, data_format == "NHWC", "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, kernel_size, stride, padding, 3,
                       output_size, data_format == "NDHWC", "max_unpool3d")


__all__ += ["max_unpool1d", "max_unpool2d", "max_unpool3d"]
