"""Geometric / video functional ops: affine_grid, grid_sample,
temporal_shift, zeropad2d.

Reference analogs: phi/kernels/affine_grid_kernel.h,
phi/kernels/grid_sample_kernel.h, fluid/operators/temporal_shift_op.cu,
python/paddle/nn/functional/common.py zeropad2d. TPU-first: grid_sample is
pure gather arithmetic (jnp.take along flattened spatial) — XLA lowers it
to dynamic-gathers that vectorize on the VPU; no per-pixel scalar loop.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...ops._helpers import ensure_tensor, call_op
from ...ops.registry import register_op

__all__ = ["affine_grid", "grid_sample", "temporal_shift", "zeropad2d"]


@register_op("affine_grid", "vision",
             ref="phi/kernels/affine_grid_kernel.h")
def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a sampling grid from batched affine matrices.
    theta [N,2,3] + out_shape [N,C,H,W] -> grid [N,H,W,2];
    theta [N,3,4] + out_shape [N,C,D,H,W] -> grid [N,D,H,W,3]."""
    theta = ensure_tensor(theta)
    if hasattr(out_shape, "_value"):
        out_shape = [int(s) for s in np.asarray(out_shape._value)]
    out_shape = [int(s) for s in out_shape]

    def line(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    def fn(th):
        if th.shape[-2:] == (2, 3):
            N, _, H, W = out_shape
            ys, xs = jnp.meshgrid(line(H), line(W), indexing="ij")
            base = jnp.stack([xs, ys, jnp.ones_like(xs)], axis=-1)  # H,W,3
            grid = jnp.einsum("hwk,njk->nhwj", base, th)
            return grid.astype(th.dtype)
        N, _, D, H, W = out_shape
        zs, ys, xs = jnp.meshgrid(line(D), line(H), line(W), indexing="ij")
        base = jnp.stack([xs, ys, zs, jnp.ones_like(xs)], axis=-1)
        grid = jnp.einsum("dhwk,njk->ndhwj", base, th)
        return grid.astype(th.dtype)

    return call_op("affine_grid", fn, (theta,))


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def _reflect(x, size, align_corners):
    if size == 1:
        return jnp.zeros_like(x)
    if align_corners:
        span = 2.0 * (size - 1)
        x = jnp.abs(x) % span
        return jnp.where(x > size - 1, span - x, x)
    span = 2.0 * size
    x = jnp.abs(x + 0.5) % span
    x = jnp.where(x > size, span - x, x) - 0.5
    return jnp.clip(x, 0, size - 1)


@register_op("grid_sample", "vision",
             ref="phi/kernels/grid_sample_kernel.h")
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x at grid locations. 4-D: x [N,C,H,W], grid [N,Ho,Wo,2]
    (last dim = (x, y) in [-1, 1]); 5-D: x [N,C,D,H,W],
    grid [N,Do,Ho,Wo,3]."""
    x = ensure_tensor(x)
    grid = ensure_tensor(grid)
    ndim_sp = grid._value.shape[-1]
    if ndim_sp not in (2, 3):
        raise ValueError("grid last dim must be 2 or 3")

    def fn(v, g):
        N, C = v.shape[0], v.shape[1]
        spatial = v.shape[2:]  # (H,W) or (D,H,W)
        n = len(spatial)
        g32 = g.astype(jnp.float32)
        # grid's last axis orders coords fastest-varying-first: (x, y[, z])
        coords = [_unnormalize(g32[..., n - 1 - d], spatial[d],
                               align_corners) for d in range(n)]

        def resolve(cs):
            """cs: list of float coords per dim -> (int idx per dim, valid)"""
            idxs, valid = [], None
            for d, c in enumerate(cs):
                size = spatial[d]
                if padding_mode == "border":
                    c = jnp.clip(c, 0, size - 1)
                elif padding_mode == "reflection":
                    c = _reflect(c, size, align_corners)
                ok = (c >= 0) & (c <= size - 1)
                valid = ok if valid is None else (valid & ok)
                idxs.append(jnp.clip(c, 0, size - 1).astype(jnp.int32))
            return idxs, valid

        def gather(idxs):
            flat = jnp.zeros_like(idxs[0])
            for d in range(n):
                flat = flat * spatial[d] + idxs[d]
            vflat = v.reshape(N, C, -1)  # [N,C,P]
            fl = flat.reshape(N, -1)     # [N,Q]
            out = jnp.take_along_axis(vflat, fl[:, None, :], axis=2)
            return out.reshape((N, C) + flat.shape[1:])

        if mode == "nearest":
            idxs, valid = resolve([jnp.floor(c + 0.5) for c in coords])
            out = gather(idxs)
            if padding_mode == "zeros":
                out = out * valid[:, None].astype(v.dtype)
            return out.astype(v.dtype)

        # bilinear / trilinear: blend the 2^n corners
        lo = [jnp.floor(c) for c in coords]
        frac = [c - l for c, l in zip(coords, lo)]
        out = 0.0
        for corner in range(2 ** n):
            bits = [(corner >> d) & 1 for d in range(n)]
            cs = [l + b for l, b in zip(lo, bits)]
            w = 1.0
            for d in range(n):
                w = w * (frac[d] if bits[d] else (1.0 - frac[d]))
            idxs, valid = resolve(cs)
            g_val = gather(idxs)
            if padding_mode == "zeros":
                w = w * valid.astype(jnp.float32)
            out = out + g_val.astype(jnp.float32) * w[:, None]
        return out.astype(v.dtype)

    return call_op("grid_sample", fn, (x, grid))


@register_op("temporal_shift", "video",
             ref="fluid/operators/temporal_shift_op.cu")
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM channel shift: within each segment of seg_num frames, the first
    `shift_ratio` of channels take the previous frame (out[t] = x[t-1]),
    the next `shift_ratio` take the following frame (out[t] = x[t+1]);
    frames shifted in from outside the segment are zero."""
    x = ensure_tensor(x)

    def fn(v):
        nhwc = data_format == "NHWC"
        if nhwc:
            v = jnp.transpose(v, (0, 3, 1, 2))
        NT, C, H, W = v.shape
        N = NT // seg_num
        v5 = v.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        pad = jnp.zeros((N, 1, C, H, W), v.dtype)
        prev = jnp.concatenate([pad, v5[:, :-1]], axis=1)   # out[t] = x[t-1]
        nxt = jnp.concatenate([v5[:, 1:], pad], axis=1)     # out[t] = x[t+1]
        out = jnp.concatenate([prev[:, :, :c1], nxt[:, :, c1:c2],
                               v5[:, :, c2:]], axis=2)
        out = out.reshape(NT, C, H, W)
        if nhwc:
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return call_op("temporal_shift", fn, (x,))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad the last two spatial dims; padding = [left, right, top,
    bottom] (reference: python/paddle/nn/functional/common.py zeropad2d)."""
    x = ensure_tensor(x)
    left, right, top, bottom = [int(p) for p in padding]

    def fn(v):
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (top, bottom), (left, right)]
        else:
            cfg = [(0, 0), (top, bottom), (left, right), (0, 0)]
        return jnp.pad(v, cfg)

    return call_op("zeropad2d", fn, (x,))
