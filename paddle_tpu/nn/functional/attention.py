"""Scaled-dot-product attention functional.

Reference analog: the fused attention path (fluid/operators/fused/
fused_attention_op.cu, fmha_ref.h). TPU-first: defaults to the Pallas
flash-attention kernel on TPU (paddle_tpu/kernels/flash_attention.py) and a
plain XLA softmax(QK^T)V fallback elsewhere / for odd shapes.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, call_op, const_input
from ...ops.registry import register_op

__all__ = ["scaled_dot_product_attention"]


class _ShapeMeta:
    """Shape/ndim view for kernel eligibility checks that must not force a
    deferred fusion placeholder's buffer."""

    __slots__ = ("ndim", "shape")

    def __init__(self, ndim, shape):
        self.ndim = ndim
        self.shape = shape


def _plain_attention(q, k, v, mask, is_causal, scale, dropout_p=0.0,
                     dropout_key=None):
    # q,k,v: [B, N, H, D] (paddle layout: batch, seq, heads, head_dim)
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, N, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhnd,bhmd->bhnm", qt, kt) * scale
    if is_causal:
        n, m = scores.shape[-2], scores.shape[-1]
        # bottom-right alignment: with cached keys (m > n), query i sits at
        # absolute position i + (m - n) and may attend to keys <= that
        q_pos = jnp.arange(n)[:, None] + (m - n)
        k_pos = jnp.arange(m)[None, :]
        scores = jnp.where(q_pos >= k_pos, scores,
                           jnp.asarray(-1e30, scores.dtype))
    if mask is not None:
        if mask.dtype == jnp.bool_.dtype:
            scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1) \
        .astype(scores.dtype)
    if dropout_p and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = probs * keep.astype(probs.dtype) / \
            jnp.asarray(1.0 - dropout_p, probs.dtype)
    out = jnp.einsum("bhnm,bhmd->bhnd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


@register_op("scaled_dot_product_attention", "fused",
             ref="fluid/operators/fused/fused_attention_op.cu")
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None,
                                 use_flash_attention=None):
    """query/key/value: [batch, seq, num_heads, head_dim] (paddle convention).

    On TPU with flash-eligible shapes this runs the Pallas flash-attention
    kernel; otherwise the XLA fallback (still one fused HLO cluster).
    """
    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    scale = 1.0 / math.sqrt(q.shape[-1])
    # the mask stays a Tensor: it becomes a dispatch INPUT below (not a
    # closure capture), and eligibility checks only need its presence —
    # never force a deferred fusion placeholder's buffer here
    mask_t = ensure_tensor(attn_mask) if attn_mask is not None else None

    # sequence/context parallelism: inside an SPMD trace binding the "sep"
    # axis, q/k/v are sequence shards — use ring attention so no chip ever
    # materializes the full sequence (paddle_tpu sep_parallel; the reference
    # has no sequence parallelism, SURVEY.md §5)
    from ...distributed.fleet.meta_parallel.mp_ops import in_spmd_axis
    if in_spmd_axis("sep"):
        eff_dropout = dropout_p if training else 0.0
        if mask_t is not None or eff_dropout:
            # a shard-local dense fallback would attend only to this chip's
            # keys — globally wrong. Fail loudly instead.
            raise NotImplementedError(
                "sequence-parallel attention (sep axis) supports causal/full "
                "attention without attn_mask or attention dropout; got "
                f"attn_mask={attn_mask is not None}, dropout_p={dropout_p}")

        def fn(qq, kk, vv):
            from ...distributed.fleet.meta_parallel.sep_parallel import (
                ring_attention)
            return ring_attention(qq, kk, vv, "sep", causal=is_causal,
                                  scale=scale)
        return call_op("ring_attention", fn, (q, k, v))

    eff_dropout = dropout_p if training else 0.0
    from ...kernels import flash_attention as fa
    # eligibility only needs shapes: answer from tensor meta (aval-safe on
    # deferred fusion placeholders) instead of forcing q/k/v buffers
    _shape_of = lambda t: _ShapeMeta(t.ndim, tuple(t.shape))
    if use_flash_attention is not False and \
            fa.is_eligible(_shape_of(q), _shape_of(k), _shape_of(v), mask_t,
                           eff_dropout, is_causal=is_causal):
        def fn(qq, kk, vv):
            return fa.flash_attention_bnhd(qq, kk, vv, causal=is_causal,
                                           scale=scale)
        return call_op("flash_attention", fn, (q, k, v))

    # the mask AND the dropout key are dispatch INPUTS (not closure
    # captures): closing over a per-batch array — or a per-call PRNG key —
    # would make every masked/regularized attention un-keyable, bypassing
    # the per-op cache and poisoning chain/step fusion cycles. The key is a
    # hoisted stream position (framework/random.rng_key_input), so dropout
    # attention promotes to the fused whole-step executable.
    eff_p = dropout_p if training else 0.0
    kd = None
    if eff_p:
        from ...framework.random import rng_key_input
        kd = rng_key_input()

    if mask_t is not None:
        if kd is not None:
            def fn(qq, kk, vv, mm, key_data):
                return _plain_attention(
                    qq, kk, vv, mm, is_causal, scale, eff_p,
                    jax.random.wrap_key_data(key_data))
            return call_op("scaled_dot_product_attention", fn,
                           (q, k, v, mask_t, kd))
        def fn(qq, kk, vv, mm):
            return _plain_attention(qq, kk, vv, mm, is_causal, scale)
        return call_op("scaled_dot_product_attention", fn, (q, k, v, mask_t))

    if kd is not None:
        def fn(qq, kk, vv, key_data):
            return _plain_attention(qq, kk, vv, None, is_causal, scale,
                                    eff_p, jax.random.wrap_key_data(key_data))
        return call_op("scaled_dot_product_attention", fn, (q, k, v, kd))

    def fn(qq, kk, vv):
        return _plain_attention(qq, kk, vv, None, is_causal, scale)
    return call_op("scaled_dot_product_attention", fn, (q, k, v))


PAGED_KERNELS = ("pallas", "blockwise", "reference")


def resolve_paged_kernel(kernel=None, head_dim=None, block_size=None,
                         interpret=False):
    """Resolve the serving attention variant: the request (explicit
    `kernel` or FLAGS_serve_attention_kernel) -> the variant that will
    actually run. An ineligible request falls back to `blockwise` (same
    math, no Mosaic constraints) and is VISIBLE: a `kernel.fallback`
    flight-recorder event attributes the demotion, never silent."""
    from ...framework.flags import _FLAGS
    from ...profiler.events import EVENTS as _EVENTS
    req = kernel or str(_FLAGS.get("FLAGS_serve_attention_kernel")
                        or "blockwise")
    if req not in PAGED_KERNELS:
        raise ValueError(
            f"unknown paged attention kernel {req!r}; expected one of "
            f"{PAGED_KERNELS}")
    actual, why = req, None
    if req == "pallas":
        from ...kernels.pallas import paged_attention as _pk
        if not _pk._HAS_PALLAS:
            # interpret mode still needs the pallas import itself
            actual, why = "blockwise", "no_pallas"
        elif not interpret:
            ok, why = _pk.is_eligible(head_dim, block_size)
            if not ok:
                actual = "blockwise"
    if actual != req:
        _EVENTS.emit("kernel.fallback", "paged_decode_attention",
                     reason="kernel_fallback",
                     detail={"requested": req, "actual": actual,
                             "why": why, "head_dim": head_dim,
                             "block_size": block_size})
    return actual


def _dense_gather_attention(qh, k_pool, v_pool, block_tables, lens,
                            block_size, k_scales=None, v_scales=None):
    """The reference oracle: gather-by-block-table into a dense
    ``[S, T, H, D]`` context, full softmax. Scores and the softmax/PV
    accumulation run in fp32 (matching `_plain_attention`) so bf16
    serving keeps its tail tokens; only the output casts back."""
    s, h, d = qh.shape
    m = block_tables.shape[1]
    t_max = m * block_size
    kg = k_pool[block_tables]                          # [S, M, bs, H, D]
    vg = v_pool[block_tables]
    if k_scales is not None:
        from ...quantization.kv_cache import dequantize
        kg = dequantize(kg, k_scales[block_tables])
        vg = dequantize(vg, v_scales[block_tables])
    else:
        kg = kg.astype(jnp.float32)
        vg = vg.astype(jnp.float32)
    keys = kg.reshape(s, t_max, h, d)
    vals = vg.reshape(s, t_max, h, d)
    scores = jnp.einsum("shd,sthd->sht", qh.astype(jnp.float32), keys) \
        / jnp.sqrt(jnp.asarray(d, jnp.float32))
    valid = jnp.arange(t_max, dtype=jnp.int32)[None, :] <= lens[:, None]
    scores = jnp.where(valid[:, None, :], scores,
                       jnp.asarray(-1e30, jnp.float32))
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("sht,sthd->shd", probs, vals).astype(qh.dtype)


def paged_decode_attention(q, k_new, v_new, k_pool, v_pool, block_tables,
                           seq_lens, active, block_size,
                           k_scales=None, v_scales=None, kernel=None,
                           interpret=False):
    """One decode step of attention against a paged block-pool KV cache
    (the PagedAttention memory model; serving/cache.py).

    q/k_new/v_new: ``[S, 1, H, D]`` — this step's projections for every
    batch slot (S is the engine's fixed max-batch slot count).
    k_pool/v_pool: ``[num_blocks, block_size, H, D]`` — one layer's pool
    (fp, or int8 with per-block-per-head `k_scales`/`v_scales`
    ``[num_blocks, H]``; quantization/kv_cache.py).
    block_tables: ``[S, max_blocks]`` int32 — per-slot ordered block ids;
    gathered position ``t`` of slot ``s`` is token position ``t`` of that
    sequence (tables are dense prefixes, padded with the null block).
    seq_lens: ``[S]`` int32 — cached tokens per slot; the new token is
    written at position ``seq_lens[s]`` and attended to (self-attention).
    active: ``[S]`` bool — inactive slots write to the reserved null
    block and their outputs are garbage by design (the engine never reads
    them).

    `kernel` selects the attention implementation (`pallas` |
    `blockwise` | `reference`, default FLAGS_serve_attention_kernel);
    every variant shares the SAME write path, masking, and fp32 softmax
    numerics — only the schedule differs. Pure jnp and shape-static: ONE
    compiled program serves every token of every tenant mix —
    join/leave/evict is a table edit, never a retrace.

    Returns ``(out [S, 1, H, D], new_k_pool, new_v_pool)`` — plus
    ``(new_k_scales, new_v_scales)`` in int8 mode.
    """
    s = q.shape[0]
    head_dim = q.shape[-1]
    quantized = k_scales is not None
    lens = jnp.where(active, seq_lens, 0).astype(jnp.int32)
    rows = jnp.arange(s, dtype=jnp.int32)
    # write the new token's K/V at (table[len // bs], len % bs); inactive
    # slots all target the null block (duplicate writes there are fine —
    # its content is never unmasked)
    write_block = jnp.where(
        active, block_tables[rows, lens // block_size], 0).astype(jnp.int32)
    write_off = lens % block_size
    if quantized:
        from ...quantization.kv_cache import quantize_block_write
        k_pool, k_scales = quantize_block_write(
            k_pool, k_scales, k_new[:, 0], write_block, write_off)
        v_pool, v_scales = quantize_block_write(
            v_pool, v_scales, v_new[:, 0], write_block, write_off)
    else:
        k_pool = k_pool.at[write_block, write_off].set(
            k_new[:, 0].astype(k_pool.dtype))
        v_pool = v_pool.at[write_block, write_off].set(
            v_new[:, 0].astype(v_pool.dtype))

    variant = resolve_paged_kernel(kernel, head_dim, block_size,
                                   interpret=interpret)
    qh = q[:, 0]                                       # [S, H, D]
    if variant == "reference":
        out = _dense_gather_attention(qh, k_pool, v_pool, block_tables,
                                      lens, block_size, k_scales, v_scales)
    elif variant == "blockwise":
        from ...kernels.pallas.paged_attention import (
            blockwise_paged_attention)
        out = blockwise_paged_attention(qh, k_pool, v_pool, block_tables,
                                        lens, block_size, k_scales,
                                        v_scales)
    else:
        from ...kernels.pallas.paged_attention import pallas_paged_attention
        out = pallas_paged_attention(qh, k_pool, v_pool, block_tables,
                                     lens, block_size, k_scales, v_scales,
                                     interpret=interpret)
    if quantized:
        return out[:, None], k_pool, v_pool, k_scales, v_scales
    return out[:, None], k_pool, v_pool


__all__ += ["paged_decode_attention", "resolve_paged_kernel",
            "PAGED_KERNELS"]


@register_op("sparse_attention", "attention",
             ref="fluid/operators/sparse_attention_op.cu")
def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-free CSR-sampled attention: for each query row i, attend only
    to the key columns listed in the CSR pattern (offset/columns per
    [batch, head]).

    TPU-first: the reference's cuSPARSE SDDMM+softmax+SpMM chain becomes a
    fixed-width gather — rows are padded to the max row degree so shapes
    stay static under jit; padded slots get -inf before the softmax.
    Layouts follow the reference: q/k/v [B, H, M, D], offset [B, H, M+1],
    columns [B, H, nnz].
    """
    import numpy as np
    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    off = np.asarray(ensure_tensor(sparse_csr_offset)._value)
    cols = np.asarray(ensure_tensor(sparse_csr_columns)._value)

    B, H, M, D = q._value.shape
    deg = np.diff(off, axis=-1)                      # [B, H, M]
    width = int(deg.max()) if deg.size else 1
    # static gather table: [B, H, M, width] column ids + validity
    col_tab = np.zeros((B, H, M, width), np.int32)
    val_tab = np.zeros((B, H, M, width), bool)
    for b in range(B):
        for h in range(H):
            for m in range(M):
                s, e = off[b, h, m], off[b, h, m + 1]
                col_tab[b, h, m, :e - s] = cols[b, h, s:e]
                val_tab[b, h, m, :e - s] = True
    # the block tables ride as dispatch inputs: captured arrays would
    # re-key the op per call even though the layout is config-derived
    col_t = const_input(col_tab)
    val_t = const_input(val_tab)

    def fn(qv, kv, vv, col_j, valid):
        scale = 1.0 / math.sqrt(D)
        kg = jnp.take_along_axis(kv[:, :, None], col_j[..., None], axis=3)
        scores = jnp.einsum("bhmd,bhmwd->bhmw", qv, kg) * scale
        scores = jnp.where(valid, scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        p = jnp.where(valid, p, 0.0)
        vg = jnp.take_along_axis(vv[:, :, None], col_j[..., None], axis=3)
        return jnp.einsum("bhmw,bhmwd->bhmd", p, vg)

    return call_op("sparse_attention", fn, (q, k, v, col_t, val_t))


__all__ += ["sparse_attention"]
