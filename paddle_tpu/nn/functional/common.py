"""Common functionals: linear, dropout, embedding, one_hot, interpolate, etc.

Reference analog: python/paddle/nn/functional/common.py + input.py. TPU-first:
linear is a plain jnp.matmul the MXU eats directly; dropout uses functional
PRNG keys (traced-key scope under jit)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...framework.random import rng_key_input
from ...framework.dtype import to_jax_dtype
from ...ops._helpers import ensure_tensor, unary, binary, nary, call_op
from ...ops.registry import register_op

__all__ = ["linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
           "embedding", "one_hot", "cosine_similarity", "pairwise_distance",
           "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
           "interpolate", "upsample", "unfold", "fold", "label_smooth",
           "bilinear", "class_center_sample", "normalize"]


@register_op("linear", "nn", ref="fluid ops: matmul_v2 + elementwise_add")
def linear(x, weight, bias=None, name=None):
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    if bias is None:
        return call_op("linear", lambda v, w: jnp.matmul(v, w), (x, weight))
    bias = ensure_tensor(bias)
    return call_op("linear", lambda v, w, b: jnp.matmul(v, w) + b,
                   (x, weight, bias))


@register_op("dropout", "nn")
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return unary("dropout", lambda v: v * (1.0 - p), x)
        return x.clone() if isinstance(x, Tensor) else x
    if p == 1.0:
        return unary("dropout", lambda v: jnp.zeros_like(v), x)
    shape = list(x.shape)     # aval-answerable: never forces a fused chain
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        mask_shape = tuple(s if i in axes else 1 for i, s in enumerate(shape))
    else:
        mask_shape = tuple(shape)
    # the key is a dispatch INPUT (one reserved stream position), not a
    # closure capture: the op keys on structure — dropout no longer
    # bypasses the executable cache or poisons fusion cycles (rng_rekey),
    # and the whole-step promoter derives the key in-graph from hoisted
    # (base, position) scalars so dropout loops fuse to ONE executable
    kd = rng_key_input()

    def fn(v, key_data):
        keep = jax.random.bernoulli(jax.random.wrap_key_data(key_data),
                                    1.0 - p, mask_shape)
        m = keep.astype(v.dtype)
        if mode == "upscale_in_train":
            return v * m / jnp.asarray(1.0 - p, v.dtype)
        return v * m
    return call_op("dropout", fn, (x, kd))


def _dropout_nd(x, p, training, data_format, spatial_dims, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x.clone()
    shape = list(x.shape)     # aval-answerable: never forces a fused chain
    if data_format.endswith("C"):  # NHWC / NDHWC: channel last
        mask_shape = tuple([shape[0]] + [1] * spatial_dims + [shape[-1]])
    else:
        mask_shape = tuple([shape[0], shape[1]] + [1] * spatial_dims)
    kd = rng_key_input()

    def fn(v, key_data):
        keep = jax.random.bernoulli(jax.random.wrap_key_data(key_data),
                                    1.0 - p, mask_shape)
        return v * keep.astype(v.dtype) / jnp.asarray(1.0 - p, v.dtype)
    return call_op("dropout_nd", fn, (x, kd))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    return _dropout_nd(x, p, training, data_format, 2, name)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    return _dropout_nd(x, p, training, data_format, 3, name)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x.clone()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    mask_shape = tuple(x.shape)   # aval-answerable
    a = (1.0 / ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    kd = rng_key_input()

    def fn(v, key_data):
        m = jax.random.bernoulli(jax.random.wrap_key_data(key_data),
                                 1.0 - p, mask_shape)
        return a * jnp.where(m, v, jnp.asarray(alpha_p, v.dtype)) + b
    return call_op("alpha_dropout", fn, (x, kd))


@register_op("embedding", "nn", ref="phi/kernels/embedding_kernel.h")
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)

    # the ids are a dispatch INPUT (not a closure capture): closing over
    # the per-batch array would make every lookup un-keyable, bypassing
    # the per-op executable cache and poisoning chain/step fusion cycles
    def fn(idx, w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros((), out.dtype), out)
        return out
    return binary("embedding", fn, x, weight)


@register_op("one_hot", "nn", differentiable=False)
def one_hot(x, num_classes, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.nn.one_hot(x._value, num_classes, dtype=jnp.float32))


@register_op("cosine_similarity", "nn")
def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)
    return binary("cosine_similarity", fn, ensure_tensor(x1), ensure_tensor(x2))


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p), axis=-1,
                                 keepdims=keepdim), 1.0 / p)
    return binary("pairwise_distance", fn, ensure_tensor(x), ensure_tensor(y))


@register_op("pixel_shuffle", "nn")
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return unary("pixel_shuffle", fn, ensure_tensor(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h // r, w // r, c * r * r)
    return unary("pixel_unshuffle", fn, ensure_tensor(x))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            return v.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        return v.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return unary("channel_shuffle", fn, ensure_tensor(x))


@register_op("interpolate", "nn")
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = ensure_tensor(x)
    v_shape = x._value.shape
    channel_last = data_format.endswith("C") and data_format != "NCHW"
    spatial = v_shape[1:-1] if channel_last else v_shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.numpy().tolist()
        out_spatial = [int(s.item()) if isinstance(s, Tensor) else int(s)
                       for s in (size if isinstance(size, (list, tuple))
                                 else [size])]
    else:
        if isinstance(scale_factor, (list, tuple)):
            out_spatial = [int(s * f) for s, f in zip(spatial, scale_factor)]
        else:
            out_spatial = [int(s * scale_factor) for s in spatial]
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(v):
        if channel_last:
            out_shape = (v.shape[0],) + tuple(out_spatial) + (v.shape[-1],)
        else:
            out_shape = v.shape[:2] + tuple(out_spatial)
        if mode == "nearest":
            return jax.image.resize(v, out_shape, method="nearest")
        if align_corners:
            # jax.image.resize has no align_corners; emulate via manual gather
            return _resize_align_corners(v, out_shape, jmode, channel_last)
        return jax.image.resize(v, out_shape, method=jmode)
    return unary("interpolate", fn, x)


def _resize_align_corners(v, out_shape, method, channel_last):
    sp_axes = list(range(1, v.ndim - 1)) if channel_last else \
        list(range(2, v.ndim))
    out = v
    for ax in sp_axes:
        in_n = out.shape[ax]
        out_n = out_shape[ax]
        if in_n == out_n:
            continue
        if out_n == 1:
            idx = jnp.zeros((1,), jnp.float32)
        else:
            idx = jnp.linspace(0.0, in_n - 1.0, out_n)
        lo = jnp.floor(idx).astype(jnp.int32)
        hi = jnp.clip(lo + 1, 0, in_n - 1)
        w = (idx - lo).astype(v.dtype)
        shape = [1] * out.ndim
        shape[ax] = out_n
        w = w.reshape(shape)
        lo_vals = jnp.take(out, lo, axis=ax)
        hi_vals = jnp.take(out, hi, axis=ax)
        out = lo_vals * (1 - w) + hi_vals * w
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format, name)


@register_op("unfold", "nn")
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = ensure_tensor(x)

    def to2(v):
        return [v, v] if isinstance(v, int) else list(v)
    ks, st, dl = to2(kernel_sizes), to2(strides), to2(dilations)
    pd = to2(paddings)
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, ((0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])))
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = v[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                       j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # [n, c, k*k, oh, ow]
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)
    return unary("unfold", fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    x = ensure_tensor(x)

    def to2(v):
        return [v, v] if isinstance(v, int) else list(v)
    os_, ks, st, dl = to2(output_sizes), to2(kernel_sizes), to2(strides), \
        to2(dilations)
    pd = to2(paddings)
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]

    def fn(v):
        n, ckk, l = v.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + pd[0] + pd[2], os_[1] + pd[1] + pd[3]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), v.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(
                    v[:, :, i, j])
        return out[:, :, pd[0]: ph - pd[2], pd[1]: pw - pd[3]]
    return unary("fold", fn, x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)
    k = label.shape[-1]

    def fn(v):
        if prior_dist is not None:
            pd = prior_dist._value if isinstance(prior_dist, Tensor) \
                else jnp.asarray(prior_dist)
            return (1 - epsilon) * v + epsilon * pd
        return (1 - epsilon) * v + epsilon / k
    return unary("label_smooth", fn, label)


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)

    def fn(a, b, w):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out
    out = nary("bilinear", fn, (x1, x2, weight))
    if bias is not None:
        out = out + ensure_tensor(bias)
    return out


def class_center_sample(label, num_classes, num_samples, group=None):
    label = ensure_tensor(label)
    pos = np.unique(np.asarray(label._value))
    num_extra = max(0, num_samples - len(pos))
    all_classes = np.arange(num_classes)
    neg_pool = np.setdiff1d(all_classes, pos)
    rng = np.random.default_rng(0)
    extra = rng.choice(neg_pool, size=min(num_extra, len(neg_pool)),
                       replace=False) if num_extra else np.empty(0, np.int64)
    sampled = np.sort(np.concatenate([pos, extra]).astype(np.int64))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    remapped = remap[np.asarray(label._value)]
    return Tensor(jnp.asarray(remapped)), Tensor(jnp.asarray(sampled))


@register_op("normalize", "nn")
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return unary("normalize",
                 lambda v: v / jnp.maximum(
                     jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=axis,
                                       keepdims=True), 1.0 / p), epsilon), x)
