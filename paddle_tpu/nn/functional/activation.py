"""Activation functionals. Reference analog: python/paddle/nn/functional/
activation.py over phi activation kernels. All are single fused XLA
expressions (VPU-friendly, fused into adjacent matmuls by XLA)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, unary, binary, call_op
from ...ops.registry import register_op

__all__ = ["relu", "relu_", "relu6", "gelu", "sigmoid", "tanh", "softmax",
           "log_softmax", "silu", "swish", "hardswish", "hardsigmoid",
           "leaky_relu", "elu", "celu", "selu", "prelu", "softplus",
           "softsign", "hardtanh", "mish", "tanhshrink", "hardshrink",
           "softshrink", "glu", "maxout", "thresholded_relu", "log_sigmoid",
           "gumbel_softmax", "rrelu"]


@register_op("relu", "activation", ref="phi/kernels/activation_kernel.h")
def relu(x, name=None):
    return unary("relu", lambda v: jnp.maximum(v, 0), x)


def relu_(x, name=None):
    out = relu(x)
    x._value, x._grad_node, x._out_index = out._value, out._grad_node, out._out_index
    return x


@register_op("relu6", "activation")
def relu6(x, name=None):
    return unary("relu6", lambda v: jnp.clip(v, 0, 6), x)


@register_op("gelu", "activation")
def gelu(x, approximate=False, name=None):
    return unary("gelu", lambda v: jax.nn.gelu(v, approximate=approximate), x)


@register_op("sigmoid", "activation")
def sigmoid(x, name=None):
    return unary("sigmoid", jax.nn.sigmoid, x)


@register_op("tanh_act", "activation")
def tanh(x, name=None):
    return unary("tanh", jnp.tanh, x)


@register_op("softmax", "activation")
def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import to_jax_dtype
    jd = to_jax_dtype(dtype) if dtype else None

    def fn(v):
        if jd is not None:
            v = v.astype(jd)
        return jax.nn.softmax(v, axis=axis)
    return unary("softmax", fn, x)


@register_op("log_softmax", "activation")
def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import to_jax_dtype
    jd = to_jax_dtype(dtype) if dtype else None

    def fn(v):
        if jd is not None:
            v = v.astype(jd)
        return jax.nn.log_softmax(v, axis=axis)
    return unary("log_softmax", fn, x)


@register_op("silu", "activation")
def silu(x, name=None):
    return unary("silu", jax.nn.silu, x)


@register_op("swish", "activation")
def swish(x, name=None):
    return unary("swish", jax.nn.silu, x)


@register_op("hardswish", "activation")
def hardswish(x, name=None):
    return unary("hardswish", lambda v: v * jnp.clip(v + 3, 0, 6) / 6, x)


@register_op("hardsigmoid", "activation")
def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    return unary("hardsigmoid", lambda v: jnp.clip(slope * v + offset, 0, 1), x)


@register_op("leaky_relu", "activation")
def leaky_relu(x, negative_slope=0.01, name=None):
    return unary("leaky_relu",
                 lambda v: jnp.where(v >= 0, v, negative_slope * v), x)


@register_op("elu", "activation")
def elu(x, alpha=1.0, name=None):
    return unary("elu", lambda v: jax.nn.elu(v, alpha=alpha), x)


@register_op("celu", "activation")
def celu(x, alpha=1.0, name=None):
    return unary("celu", lambda v: jax.nn.celu(v, alpha=alpha), x)


@register_op("selu", "activation")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return unary("selu",
                 lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x)


@register_op("prelu", "activation")
def prelu(x, weight, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)

    def fn(v, w):
        if w.size > 1:
            ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
            shape = [1] * v.ndim
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(v >= 0, v, w * v)
    return call_op("prelu", fn, (x, weight))


@register_op("softplus", "activation")
def softplus(x, beta=1, threshold=20, name=None):
    return unary("softplus",
                 lambda v: jnp.where(beta * v > threshold, v,
                                     jnp.log1p(jnp.exp(beta * v)) / beta), x)


@register_op("softsign", "activation")
def softsign(x, name=None):
    return unary("softsign", jax.nn.soft_sign, x)


@register_op("hardtanh", "activation")
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return unary("hardtanh", lambda v: jnp.clip(v, min, max), x)


@register_op("mish", "activation")
def mish(x, name=None):
    return unary("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)), x)


@register_op("tanhshrink", "activation")
def tanhshrink(x, name=None):
    return unary("tanhshrink", lambda v: v - jnp.tanh(v), x)


@register_op("hardshrink", "activation")
def hardshrink(x, threshold=0.5, name=None):
    return unary("hardshrink",
                 lambda v: jnp.where(jnp.abs(v) > threshold, v, 0), x)


@register_op("softshrink", "activation")
def softshrink(x, threshold=0.5, name=None):
    return unary("softshrink",
                 lambda v: jnp.where(v > threshold, v - threshold,
                                     jnp.where(v < -threshold, v + threshold,
                                               0)), x)


@register_op("glu", "activation")
def glu(x, axis=-1, name=None):
    return unary("glu", lambda v: jax.nn.glu(v, axis=axis), x)


@register_op("maxout", "activation")
def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = list(v.shape)
        new_shape[ax:ax + 1] = [c // groups, groups]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return unary("maxout", fn, x)


@register_op("thresholded_relu", "activation")
def thresholded_relu(x, threshold=1.0, name=None):
    return unary("thresholded_relu",
                 lambda v: jnp.where(v > threshold, v, 0), x)


@register_op("log_sigmoid", "activation")
def log_sigmoid(x, name=None):
    return unary("log_sigmoid", jax.nn.log_sigmoid, x)


@register_op("gumbel_softmax", "activation")
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    # the gumbel noise samples in-graph from a hoisted stream position
    # (same fold_in key bits as the old stateful draw) — the op keys on
    # structure and promotes instead of re-keying every call (rng_rekey)
    from ...framework.random import rng_key_input
    x = ensure_tensor(x)
    kd = rng_key_input()

    def fn(v, key_data):
        g = jax.random.gumbel(jax.random.wrap_key_data(key_data),
                              v.shape, jnp.float32)
        y = jax.nn.softmax((v + g.astype(v.dtype)) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y)
            onehot = jnp.put_along_axis(onehot, idx,
                                        jnp.ones((), y.dtype), axis=axis,
                                        inplace=False)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y
    return call_op("gumbel_softmax", fn, (x, kd))


@register_op("rrelu", "activation")
def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    x = ensure_tensor(x)
    if training:
        # training-mode slopes sample in-graph from a hoisted stream
        # position (bit-identical to the old stateful draw)
        from ...framework.random import rng_key_input
        kd = rng_key_input()

        def fn(v, key_data):
            a = jax.random.uniform(jax.random.wrap_key_data(key_data),
                                   v.shape, jnp.float32, lower, upper)
            return jnp.where(v >= 0, v, a.astype(v.dtype) * v)
        return call_op("rrelu", fn, (x, kd))
    mid = (lower + upper) / 2.0

    def fn(v):
        return jnp.where(v >= 0, v, mid * v)
    return unary("rrelu", fn, x)


def _make_inplace(fn, name):
    """Inplace variant: rebind the input Tensor's value + autograd edge to
    the op result (same contract as ops/tail.py _inplace)."""
    def op_(x, *args, **kwargs):
        from ...framework.autograd import is_grad_enabled, AccumulationNode
        if is_grad_enabled() and not x.stop_gradient and \
                (x._grad_node is None
                 or isinstance(x._grad_node, AccumulationNode)):
            raise RuntimeError(
                f"a leaf Tensor that requires grad is used in an in-place "
                f"operation ({name}); wrap the update in paddle.no_grad()")
        out = fn(x, *args, **kwargs)
        x._value = out._value
        if not out.stop_gradient:
            x._grad_node = out._grad_node
            x._out_index = out._out_index
            x.stop_gradient = False
        return x
    op_.__name__ = name
    return op_


elu_ = _make_inplace(elu, "elu_")
softmax_ = _make_inplace(softmax, "softmax_")
tanh_ = _make_inplace(tanh, "tanh_")

__all__ += ["elu_", "softmax_", "tanh_"]
