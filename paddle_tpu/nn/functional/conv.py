"""Convolution functionals over jax.lax.conv_general_dilated.

Reference analog: python/paddle/nn/functional/conv.py over phi conv kernels
(conv_kernel.h, gpudnn). TPU-first: one lax conv op per call — XLA lowers it
onto the MXU with its own im2col-free tiling; no cudnn-algo selection needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, call_op
from ...ops.registry import register_op

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _norm_padding(padding, n):
    """Returns (lax_padding, is_same) where lax_padding is 'SAME'/'VALID' or
    explicit [(lo,hi)] per spatial dim."""
    if isinstance(padding, str):
        return padding.upper(), padding.upper() == "SAME"
    if isinstance(padding, int):
        return [(padding, padding)] * n, False
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding], False
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)], False
    # paddle also accepts [[0,0],[0,0],[lo,hi],...] including batch/channel
    if len(padding) == n + 2:
        return [tuple(p) for p in padding[2:]], False
    return [tuple(p) for p in padding], False


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else \
            ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else \
        ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, n,
          op_name):
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    pad, _ = _norm_padding(padding, n)
    dn_spec = _dim_numbers(n, channel_last)

    def fn(v, w, *maybe_bias):
        # paddle weight layout is [out_c, in_c/groups, *spatial] (OIHW-style);
        # lax wants per dn_spec — OIHW works directly for channel-first, and
        # for channel-last we transpose to HWIO.
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w = jnp.transpose(w, perm)
        dn = lax.conv_dimension_numbers(v.shape, w.shape, dn_spec)
        # no preferred_element_type override: the TPU MXU already
        # accumulates bf16 convs in f32 internally, and the f32 hint breaks
        # jax's conv transpose rule (f32 cotangent vs bf16 operands)
        out = lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return call_op(op_name, fn, (x, weight, ensure_tensor(bias)))
    return call_op(op_name, fn, (x, weight))


@register_op("conv1d", "conv", ref="phi/kernels/conv_kernel.h")
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, df, 1,
                 "conv1d")


@register_op("conv2d", "conv", ref="phi/kernels/conv_kernel.h")
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 2, "conv2d")


@register_op("conv3d", "conv")
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 3, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, data_format, n, op_name,
                    output_size=None):
    x = ensure_tensor(x)
    weight = ensure_tensor(weight)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    pad, is_same = _norm_padding(padding, n)
    out_pad = _norm_tuple(output_padding, n) if output_padding else (0,) * n
    dn_spec = _dim_numbers(n, channel_last)

    def fn(v, w, *maybe_bias):
        # paddle transpose-conv weight layout: [in_c, out_c/groups, *spatial]
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (0, 1)  # spatial..., I, O
            wt = jnp.transpose(w, perm)
        else:
            wt = w
        if isinstance(pad, str):
            lax_pad = pad
        else:
            # gradient-of-conv padding: effective kernel k_eff = d*(k-1)+1
            lax_pad = []
            for i in range(n):
                k_eff = dilations[i] * (w.shape[2 + i] - 1) + 1
                lo, hi = pad[i]
                lax_pad.append((k_eff - 1 - lo,
                                k_eff - 1 - hi + out_pad[i]))
        if groups == 1:
            dn = lax.conv_dimension_numbers(
                v.shape,
                wt.shape if channel_last else
                (w.shape[1], w.shape[0]) + w.shape[2:],
                dn_spec)
            # lax transposed conv: dilate lhs by stride
            kernel = wt if channel_last else jnp.swapaxes(w, 0, 1)
            kernel = jnp.flip(kernel, axis=tuple(range(n)) if channel_last
                              else tuple(range(2, 2 + n)))
            out = lax.conv_general_dilated(
                v, kernel, window_strides=(1,) * n, padding=lax_pad,
                lhs_dilation=strides, rhs_dilation=dilations,
                dimension_numbers=dn)
        else:
            outs = []
            vg = jnp.split(v, groups, axis=-1 if channel_last else 1)
            wgs = jnp.split(w, groups, axis=0)
            for gi in range(groups):
                wk = jnp.swapaxes(wgs[gi], 0, 1)
                if channel_last:
                    wk = jnp.transpose(wgs[gi], tuple(range(2, 2 + n)) + (0, 1))
                    wk = jnp.flip(wk, axis=tuple(range(n)))
                else:
                    wk = jnp.flip(wk, axis=tuple(range(2, 2 + n)))
                dn = lax.conv_dimension_numbers(vg[gi].shape, wk.shape, dn_spec)
                outs.append(lax.conv_general_dilated(
                    vg[gi], wk, window_strides=(1,) * n, padding=lax_pad,
                    lhs_dilation=strides, rhs_dilation=dilations,
                    dimension_numbers=dn))
            out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        out = call_op(op_name, fn, (x, weight, ensure_tensor(bias)))
    else:
        out = call_op(op_name, fn, (x, weight))
    if output_size is not None:
        # crop/verify to requested spatial size
        want = output_size if isinstance(output_size, (list, tuple)) \
            else [output_size] * n
        sl = [slice(None)] * out.ndim
        base = 1 if channel_last else 2
        for i in range(n):
            sl[base + i] = slice(0, int(want[i]))
        from ...ops.manipulation import strided_slice  # noqa
        out = out[tuple(sl)]
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, df, 1, "conv1d_transpose",
                           output_size)


@register_op("conv2d_transpose", "conv")
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2,
                           "conv2d_transpose", output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3,
                           "conv3d_transpose", output_size)
