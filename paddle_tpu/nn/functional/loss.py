"""Loss functionals. Reference analog: python/paddle/nn/functional/loss.py
over phi cross_entropy/bce/... kernels."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, unary, binary, nary, call_op, \
    const_input
from ...ops.registry import register_op

__all__ = ["cross_entropy", "softmax_with_cross_entropy", "nll_loss",
           "binary_cross_entropy", "binary_cross_entropy_with_logits",
           "mse_loss", "l1_loss", "smooth_l1_loss", "kl_div", "margin_ranking_loss",
           "hinge_embedding_loss", "cosine_embedding_loss", "ctc_loss",
           "triplet_margin_loss", "log_loss", "square_error_cost",
           "sigmoid_focal_loss", "dice_loss", "npair_loss"]


def _apply_reduction(out_fn, reduction):
    if reduction == "mean":
        return lambda *a: jnp.mean(out_fn(*a))
    if reduction == "sum":
        return lambda *a: jnp.sum(out_fn(*a))
    return out_fn


@register_op("cross_entropy", "loss",
             ref="phi/kernels/cross_entropy_kernel.h; python/paddle/nn/functional/loss.py cross_entropy")
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    n_classes = input.shape[axis]

    if soft_label:
        def fn(logits, lab, *w):
            logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
                else jnp.log(jnp.clip(logits, 1e-30, None))
            loss = -jnp.sum(lab * logp, axis=axis)
            if w:
                cw = jnp.sum(lab * w[0], axis=axis)
                loss = loss * cw
            return loss
        args = (input, label) if weight is None else \
            (input, label, ensure_tensor(weight))
        return call_op("cross_entropy", _apply_reduction(fn, reduction), args)

    # shape-only peek (aval-safe: must not force a deferred placeholder)
    if label.ndim == input.ndim and label.shape[axis] == 1:
        from ...ops.manipulation import squeeze as _squeeze
        label = _squeeze(label, axis)

    # labels are a dispatch INPUT (not a closure capture): closing over the
    # per-batch array would make every loss un-keyable, bypassing the
    # per-op cache and poisoning chain/step fusion cycles
    def fn(logits, raw_lab, *w):
        lab_idx = jnp.clip(raw_lab, 0, n_classes - 1).astype(jnp.int32)
        from ...kernels import cross_entropy as fused_ce
        if (not w and label_smoothing == 0.0 and use_softmax
                and logits.ndim == 2 and axis in (-1, 1)
                and lab_idx.ndim == 1
                and fused_ce.is_eligible(logits, lab_idx)):
            # vocab-blocked Pallas kernel: no [rows, V] log-softmax in HBM
            nll = fused_ce.fused_softmax_cross_entropy(logits, lab_idx)
            return fused_ce.masked_reduce(nll, raw_lab, ignore_index,
                                          reduction)
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.clip(logits, 1e-30, None))
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(lab_idx, axis), axis=axis)
        picked = jnp.squeeze(picked, axis)
        if label_smoothing > 0.0:
            smooth = jnp.mean(logp, axis=axis)
            nll = -(1.0 - label_smoothing) * picked - label_smoothing * smooth
        else:
            nll = -picked
        valid = (raw_lab != ignore_index)
        nll = jnp.where(valid, nll, 0.0)
        if w:
            cw = jnp.take(w[0], lab_idx, axis=0)
            nll = nll * cw
            if reduction == "mean":
                denom = jnp.sum(jnp.where(valid, cw, 0.0))
                return jnp.sum(nll) / jnp.maximum(denom, 1e-12)
        if reduction == "mean":
            denom = jnp.sum(valid.astype(logits.dtype))
            return jnp.sum(nll) / jnp.maximum(denom, 1.0)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    args = (input, label) if weight is None else \
        (input, label, ensure_tensor(weight))
    return call_op("cross_entropy", fn, args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    from .activation import softmax as softmax_fn
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # reference returns loss with trailing 1-dim
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


@register_op("nll_loss", "loss")
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input = ensure_tensor(input)
    label = ensure_tensor(label)

    # labels are a dispatch INPUT (not a closure capture): closing over the
    # per-batch array would make every loss un-keyable, bypassing the
    # per-op cache and poisoning chain/step fusion cycles
    def fn(logp, lab_v, *w):
        lab_idx = jnp.clip(lab_v, 0, logp.shape[1] - 1).astype(jnp.int32)
        picked = jnp.take_along_axis(logp, lab_idx[:, None], axis=1)[:, 0] \
            if logp.ndim == 2 else jnp.take_along_axis(
                logp, jnp.expand_dims(lab_idx, 1), axis=1).squeeze(1)
        nll = -picked
        valid = lab_v != ignore_index
        nll = jnp.where(valid, nll, 0.0)
        if w:
            cw = jnp.take(w[0], lab_idx, axis=0)
            nll = nll * cw
            if reduction == "mean":
                return jnp.sum(nll) / jnp.maximum(
                    jnp.sum(jnp.where(valid, cw, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(
                jnp.sum(valid.astype(logp.dtype)), 1.0)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll
    args = (input, label) if weight is None else \
        (input, label, ensure_tensor(weight))
    return call_op("nll_loss", fn, args)


@register_op("binary_cross_entropy", "loss")
def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def fn(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return loss
    args = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return call_op("binary_cross_entropy", _apply_reduction(fn, reduction),
                   tuple(args))


@register_op("binary_cross_entropy_with_logits", "loss")
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    pw = ensure_tensor(pos_weight)._value if pos_weight is not None else None

    def fn(x, y, *w):
        # numerically-stable BCE-with-logits
        neg_abs = -jnp.abs(x)
        base = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(neg_abs))
        if pw is not None:
            log_sig = jax.nn.log_sigmoid(x)
            log_sig_neg = jax.nn.log_sigmoid(-x)
            base = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        if w:
            base = base * w[0]
        return base
    args = [ensure_tensor(logit), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return call_op("binary_cross_entropy_with_logits",
                   _apply_reduction(fn, reduction), tuple(args))


@register_op("mse_loss", "loss")
def mse_loss(input, label, reduction="mean", name=None):
    return call_op("mse_loss",
                   _apply_reduction(lambda a, b: jnp.square(a - b), reduction),
                   (ensure_tensor(input), ensure_tensor(label)))


@register_op("l1_loss", "loss")
def l1_loss(input, label, reduction="mean", name=None):
    return call_op("l1_loss",
                   _apply_reduction(lambda a, b: jnp.abs(a - b), reduction),
                   (ensure_tensor(input), ensure_tensor(label)))


@register_op("smooth_l1_loss", "loss")
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        return jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return call_op("smooth_l1_loss", _apply_reduction(fn, reduction),
                   (ensure_tensor(input), ensure_tensor(label)))


@register_op("kl_div", "loss")
def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, y):
        loss = y * (jnp.log(jnp.clip(y, 1e-12, None)) - logp)
        return loss
    base = _apply_reduction(fn, reduction if reduction != "batchmean" else "sum")
    out = call_op("kl_div", base,
                  (ensure_tensor(input), ensure_tensor(label)))
    if reduction == "batchmean":
        out = out / ensure_tensor(input).shape[0]
    return out


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def fn(a, b, y):
        return jnp.maximum(-y * (a - b) + margin, 0.0)
    return call_op("margin_ranking_loss", _apply_reduction(fn, reduction),
                   (ensure_tensor(input), ensure_tensor(other),
                    ensure_tensor(label)))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def fn(x, y):
        return jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0))
    return call_op("hinge_embedding_loss", _apply_reduction(fn, reduction),
                   (ensure_tensor(input), ensure_tensor(label)))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
    return call_op("cosine_embedding_loss", _apply_reduction(fn, reduction),
                   (ensure_tensor(input1), ensure_tensor(input2),
                    ensure_tensor(label)))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v + epsilon), p),
                                     axis=-1), 1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return jnp.maximum(d_pos - d_neg + margin, 0.0)
    return call_op("triplet_margin_loss", _apply_reduction(fn, reduction),
                   (ensure_tensor(input), ensure_tensor(positive),
                    ensure_tensor(negative)))


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, y):
        return -(y * jnp.log(p + epsilon) + (1 - y) * jnp.log(1 - p + epsilon))
    return call_op("log_loss", fn, (ensure_tensor(input), ensure_tensor(label)))


def square_error_cost(input, label):
    return call_op("square_error_cost", lambda a, b: jnp.square(a - b),
                   (ensure_tensor(input), ensure_tensor(label)))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(x, y, *n):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return loss
    args = [ensure_tensor(logit), ensure_tensor(label)]
    if normalizer is not None:
        args.append(ensure_tensor(normalizer))
    return call_op("sigmoid_focal_loss", _apply_reduction(fn, reduction),
                   tuple(args))


def dice_loss(input, label, epsilon=1e-5, name=None):
    input = ensure_tensor(input)
    # the label rides as a dispatch input (the nll_loss/cross_entropy
    # pattern): a closure-captured label array would re-key every call
    lab = const_input(label)

    def fn(p, lv):
        y = jax.nn.one_hot(lv.squeeze(-1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = 2.0 * jnp.sum(p * y, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y, axis=reduce_dims)
        return jnp.mean(1.0 - (inter + epsilon) / (union + epsilon))
    return call_op("dice_loss", fn, (input, lab))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    anchor = ensure_tensor(anchor)
    positive = ensure_tensor(positive)
    lab_t = const_input(labels)

    def fn(a, p, lv):
        lab = lv.reshape(-1)
        batch = a.shape[0]
        sim = a @ p.T
        same = (lab[:, None] == lab[None, :]).astype(a.dtype)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) +
                        jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return ce + reg
    return call_op("npair_loss", fn, (anchor, positive, lab_t))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via dynamic-programming forward algorithm (lax.scan over time)."""
    log_probs = ensure_tensor(log_probs)     # [T, B, C] (paddle layout)
    lab_t = const_input(labels)              # [B, L]
    in_len_t = const_input(input_lengths)
    lab_len_t = const_input(label_lengths)

    def fn(lp, lab, lab_len, in_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, B, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        # extended label sequence with blanks
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = jnp.asarray(-1e30, lp.dtype)
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
        first_lab = lp[0, jnp.arange(B), ext[:, 1]]
        alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, first_lab, neg_inf))

        def logaddexp(a, b):
            return jnp.logaddexp(a, b)

        def step(alpha, lp_t):
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            ext_shift2 = jnp.concatenate(
                [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
            allow_skip = (ext != blank) & (ext != ext_shift2)
            merged = logaddexp(alpha, a_shift1)
            merged = jnp.where(allow_skip, logaddexp(merged, a_shift2), merged)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_step(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            # freeze once past each sequence's input length
            active = (t < in_len)[:, None]
            return jnp.where(active, new_alpha, alpha), None

        alpha_T, _ = jax.lax.scan(scan_step, alpha0, jnp.arange(1, T))
        end1 = jnp.take_along_axis(alpha_T, (2 * lab_len)[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        end2 = jnp.take_along_axis(alpha_T, (2 * lab_len - 1)[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        ll = jnp.logaddexp(end1, end2)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return call_op("ctc_loss", fn, (log_probs, lab_t, lab_len_t, in_len_t))


def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-label * input)); label in {-1, 1}. Reference:
    python/paddle/nn/functional/loss.py soft_margin_loss."""
    def fn(x, y):
        return jnp.log1p(jnp.exp(-y * x))
    return binary("soft_margin_loss", _apply_reduction(fn, reduction),
                  ensure_tensor(input), ensure_tensor(label, "float32"))


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    """Per-class BCE-with-logits averaged over classes (reference:
    multilabel_soft_margin_loss)."""
    def fn(x, y, *w):
        logsig = jax.nn.log_sigmoid
        per = -(y * logsig(x) + (1.0 - y) * logsig(-x))
        if w:
            per = per * w[0]
        return jnp.mean(per, axis=-1)
    args = [ensure_tensor(input), ensure_tensor(label, "float32")]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return nary("multi_label_soft_margin_loss",
                _apply_reduction(fn, reduction), args)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class hinge: mean_j max(0, margin - x[y] + x[j])^p, j != y."""
    def fn(x, y, *w):
        C = x.shape[-1]
        y = y.astype(jnp.int32)
        xy = jnp.take_along_axis(x, y[:, None], axis=-1)
        m = jnp.maximum(0.0, margin - xy + x) ** p
        if w:
            m = m * w[0][y][:, None]
        mask = jax.nn.one_hot(y, C, dtype=x.dtype)
        return jnp.sum(m * (1.0 - mask), axis=-1) / C
    args = [ensure_tensor(input), ensure_tensor(label)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return nary("multi_margin_loss", _apply_reduction(fn, reduction), args)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Triplet loss with a user distance fn (reference:
    triplet_margin_with_distance_loss). The custom callable operates on
    Tensors, so this path composes at the python level (still jittable —
    the distance fn is traced along with the rest)."""
    input = ensure_tensor(input)
    positive = ensure_tensor(positive)
    negative = ensure_tensor(negative)
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   p=2.0, swap=swap, reduction=reduction)
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    d_neg_swap = distance_function(positive, negative) if swap else None

    def fn(dp, dn, *rest):
        dn_eff = jnp.minimum(dn, rest[0]) if rest else dn
        return jnp.maximum(0.0, dp - dn_eff + margin)

    args = [ensure_tensor(d_pos), ensure_tensor(d_neg)]
    if d_neg_swap is not None:
        args.append(ensure_tensor(d_neg_swap))
    return nary("triplet_margin_with_distance_loss",
                _apply_reduction(fn, reduction), args)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over the default complete binary tree
    (reference: phi/kernels/hsigmoid_loss_kernel.h; MatrixBitCodeFunctor
    in fluid/operators/math/matrix_bit_code.h).

    Default tree: class c's code is (c + num_classes) in a heap layout;
    internal node ids are the heap path nodes minus 1 (root excluded by
    construction), bit = parity of each path node."""
    input = ensure_tensor(input)
    label = ensure_tensor(label)
    weight = ensure_tensor(weight)
    args = [input, label, weight]
    if bias is not None:
        args.append(ensure_tensor(bias))

    if path_table is not None:
        pt = const_input(path_table)
        pc = const_input(path_code)
        has_bias = bias is not None

        def fn(x, y, w, *rest):
            it = iter(rest)
            bv = next(it) if has_bias else None
            tbl = next(it)
            code = next(it).astype(jnp.float32)
            rows = tbl[y.astype(jnp.int32)] if tbl.ndim == 2 and \
                tbl.shape[0] != y.shape[0] else tbl
            codes = code[y.astype(jnp.int32)] if code.ndim == 2 and \
                code.shape[0] != y.shape[0] else code
            valid = rows >= 0
            safe = jnp.where(valid, rows, 0).astype(jnp.int32)
            wv = w[safe]                       # [B, L, D]
            logits = jnp.einsum("bld,bd->bl", wv, x)
            if bv is not None:
                logits = logits + bv.reshape(-1)[safe]
            per = jnp.where(
                valid,
                jnp.log1p(jnp.exp(-jnp.where(codes > 0, logits, -logits))),
                0.0)
            return jnp.sum(per, axis=-1, keepdims=True)
        # reference hsigmoid_loss has no reduction: per-sample cost [N, 1]
        return nary("hsigmoid_loss", fn, args + [pt, pc])

    # default complete-binary-tree path, depth = ceil(log2(num_classes))
    import math
    depth = max(1, int(math.ceil(math.log2(max(2, num_classes)))))

    def fn(x, y, w, *b):
        heap = y.astype(jnp.int32) + num_classes   # leaf heap id
        logits_sum = jnp.zeros((x.shape[0],), jnp.float32)
        node = heap
        for _ in range(depth):
            parent = node // 2
            bit = (node % 2).astype(jnp.float32)   # right child => 1
            active = parent >= 1
            nid = jnp.clip(parent - 1, 0, w.shape[0] - 1)
            logit = jnp.einsum("bd,bd->b", w[nid], x)
            if b:
                logit = logit + b[0].reshape(-1)[nid]
            # bit=1 -> sigmoid(logit), bit=0 -> sigmoid(-logit)
            term = jnp.log1p(jnp.exp(-jnp.where(bit > 0, logit, -logit)))
            logits_sum = logits_sum + jnp.where(active, term, 0.0)
            node = parent
        return logits_sum[:, None]

    # reference hsigmoid_loss has no reduction: per-sample cost [N, 1]
    return nary("hsigmoid_loss", fn, args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace-family margin softmax (reference:
    fluid/operators/margin_cross_entropy_op.cu): the target-class cosine
    is replaced by cos(m1*theta + m2) - m3, everything scaled by `scale`.
    `group` is accepted for API parity; the model-parallel class split is
    expressed via sharded logits under shard_map instead."""
    logits = ensure_tensor(logits)
    label = ensure_tensor(label)

    def fn(x, y):
        y = y.astype(jnp.int32).reshape(-1)
        cos = jnp.clip(x.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(jnp.clip(
            jnp.take_along_axis(cos, y[:, None], axis=-1), -1.0, 1.0))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(y, x.shape[-1], dtype=cos.dtype)
        adjusted = scale * (cos * (1 - onehot) + target * onehot)
        logp = jax.nn.log_softmax(adjusted, axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=-1)
        return loss, jnp.exp(logp)

    from ...ops.dispatch import call_op_multi
    loss, softmax = call_op_multi(
        "margin_cross_entropy", fn,
        (logits, label), num_outputs=2)
    if reduction == "mean":
        from ...ops import mean as _mean
        loss = _mean(loss)
    elif reduction == "sum":
        from ...ops import sum as _sum
        loss = _sum(loss)
    if return_softmax:
        return loss, softmax
    return loss


__all__ += ["soft_margin_loss", "multi_label_soft_margin_loss",
            "multi_margin_loss", "triplet_margin_with_distance_loss",
            "hsigmoid_loss", "margin_cross_entropy"]
