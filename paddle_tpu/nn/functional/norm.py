"""Normalization functionals. Reference analog: python/paddle/nn/functional/
norm.py over phi layer_norm/batch_norm kernels. TPU-first: plain jnp reductions
that XLA fuses; batch-norm running stats are updated functionally on the
wrapper tensors."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, call_op, unary, const_input
from ...ops.registry import register_op

__all__ = ["layer_norm", "batch_norm", "instance_norm", "group_norm",
           "local_response_norm", "rms_norm"]


@register_op("layer_norm", "norm", ref="phi/kernels/layer_norm_kernel.h")
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))

    def fn(v, *wb):
        m = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((v.astype(jnp.float32) - m) * jax.lax.rsqrt(var + epsilon))
        out = out.astype(v.dtype)
        if len(wb) >= 1:
            out = out * wb[0]
        if len(wb) == 2:
            out = out + wb[1]
        return out

    inputs = [x]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    if bias is not None:
        if weight is None:
            # normalize-then-bias without scale: pass ones for scale slot
            def fn_b(v, b):
                m = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
                var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
                out = ((v.astype(jnp.float32) - m) *
                       jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
                return out + b
            return call_op("layer_norm", fn_b, (x, ensure_tensor(bias)))
        inputs.append(ensure_tensor(bias))
    return call_op("layer_norm", fn, tuple(inputs))


@register_op("rms_norm", "norm")
def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    x = ensure_tensor(x)

    def fn(v, *w):
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)) \
            .astype(v.dtype)
        if w:
            out = out * w[0]
        return out
    if weight is not None:
        return call_op("rms_norm", fn, (x, ensure_tensor(weight)))
    return call_op("rms_norm", fn, (x,))


@register_op("batch_norm", "norm", ref="phi/kernels/batch_norm_kernel.h")
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    x = ensure_tensor(x)
    channel_axis = x.ndim - 1 if data_format.endswith("C") and \
        data_format != "NCHW" else 1
    if x.ndim == 2:
        channel_axis = 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # update running stats in place on the wrapper, outside the grad graph
        # (reference semantics: running = momentum*running + (1-momentum)*batch)
        mean_obs = jnp.mean(x._value.astype(jnp.float32), axis=reduce_axes)
        var_obs = jnp.var(x._value.astype(jnp.float32), axis=reduce_axes)
        if running_mean is not None:
            rm = running_mean._value.astype(jnp.float32)
            running_mean._value = (momentum * rm + (1 - momentum) * mean_obs) \
                .astype(running_mean._value.dtype)
        if running_var is not None:
            n = x.size // x.shape[channel_axis]
            unbiased = var_obs * n / max(n - 1, 1)
            rv = running_var._value.astype(jnp.float32)
            running_var._value = (momentum * rv + (1 - momentum) * unbiased) \
                .astype(running_var._value.dtype)
        frozen = ()
    else:
        # eval-mode stats ride as dispatch inputs: a closure-captured
        # running-stat array would re-key the op every call (R1) — as
        # inputs, eval batch_norm keys on structure and fuses
        frozen = (const_input(running_mean), const_input(running_var))

    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]

    def fn(v, *rest):
        vf = v.astype(jnp.float32)
        if use_batch_stats:
            # batch stats inside the traced fn so grads flow through mean/var
            m = jnp.mean(vf, axis=reduce_axes).reshape(shape)
            var = jnp.var(vf, axis=reduce_axes).reshape(shape)
            wb = rest
        else:
            m = rest[0].astype(jnp.float32).reshape(shape)
            var = rest[1].astype(jnp.float32).reshape(shape)
            wb = rest[2:]
        out = ((vf - m) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    inputs = [x] + list(frozen)
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    if bias is not None:
        inputs.append(ensure_tensor(bias))
    return call_op("batch_norm", fn, tuple(inputs))


@register_op("instance_norm", "norm")
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_axis = 1
    reduce_axes = tuple(range(2, x.ndim))
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]

    def fn(v, *wb):
        vf = v.astype(jnp.float32)
        m = jnp.mean(vf, axis=reduce_axes, keepdims=True)
        var = jnp.var(vf, axis=reduce_axes, keepdims=True)
        out = ((vf - m) * jax.lax.rsqrt(var + eps)).astype(v.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    inputs = [x]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    if bias is not None:
        inputs.append(ensure_tensor(bias))
    return call_op("instance_norm", fn, tuple(inputs))


@register_op("group_norm", "norm")
def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channel_last = data_format.endswith("C") and data_format != "NCHW"
    ch_axis = x.ndim - 1 if channel_last else 1
    c = x.shape[ch_axis]
    shape = [1] * x.ndim
    shape[ch_axis] = c

    def fn(v, *wb):
        if channel_last:
            vm = jnp.moveaxis(v, -1, 1)
        else:
            vm = v
        n = vm.shape[0]
        grouped = vm.reshape((n, num_groups, c // num_groups) + vm.shape[2:])
        gf = grouped.astype(jnp.float32)
        axes = tuple(range(2, gf.ndim))
        m = jnp.mean(gf, axis=axes, keepdims=True)
        var = jnp.var(gf, axis=axes, keepdims=True)
        out = ((gf - m) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        out = out.reshape(vm.shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    inputs = [x]
    if weight is not None:
        inputs.append(ensure_tensor(weight))
    if bias is not None:
        inputs.append(ensure_tensor(bias))
    return call_op("group_norm", fn, tuple(inputs))


@register_op("local_response_norm", "norm")
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def fn(v):
        sq = jnp.square(v)
        ch_axis = 1
        c = v.shape[ch_axis]
        half = size // 2
        pad_width = [(0, 0)] * v.ndim
        pad_width[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_width)
        acc = jnp.zeros_like(v)
        for i in range(size):
            acc = acc + jax.lax.slice_in_dim(padded, i, i + c, axis=ch_axis)
        div = jnp.power(k + alpha * acc, beta)
        return v / div
    return unary("local_response_norm", fn, x)
