"""Sequence utilities: sequence_mask, gather_tree.

Reference analogs: phi/kernels/sequence_mask_kernel.h (fluid
sequence_mask_op) and phi/kernels/gather_tree_kernel.h (beam-search
backtrace). TPU-first: gather_tree's per-beam backward walk is a
`lax.scan` over time — one compiled loop, no host round-trips.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ...framework.core import Tensor
from ...framework.dtype import to_jax_dtype
from ...ops._helpers import ensure_tensor, call_op
from ...ops.registry import register_op

__all__ = ["sequence_mask", "gather_tree"]


@register_op("sequence_mask", "sequence", differentiable=False,
             ref="phi/kernels/sequence_mask_kernel.h")
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """mask[..., j] = j < x[...]. If maxlen is None, use max(x)."""
    x = ensure_tensor(x)
    xv = x._value
    if maxlen is None:
        maxlen = int(jnp.max(xv))
    elif hasattr(maxlen, "_value"):
        maxlen = int(maxlen._value)
    r = jnp.arange(int(maxlen))
    mask = r[None, :] < xv.reshape(-1, 1)
    mask = mask.reshape(tuple(xv.shape) + (int(maxlen),))
    return Tensor(mask.astype(to_jax_dtype(dtype)), stop_gradient=True)


@register_op("gather_tree", "sequence", differentiable=False,
             ref="phi/kernels/gather_tree_kernel.h")
def gather_tree(ids, parents, name=None):
    """Reconstruct full beam-search sequences from per-step ids and parent
    beam indices. ids/parents: [max_time, batch, beam]."""
    ids = ensure_tensor(ids)
    parents = ensure_tensor(parents)

    def fn(idv, parv):
        T = idv.shape[0]
        beam = jnp.arange(idv.shape[2], dtype=parv.dtype)
        beam0 = jnp.broadcast_to(beam, idv.shape[1:])  # [batch, beam]

        def step(carry, t):
            cur_beam = carry
            rev_t = T - 1 - t
            out_t = jnp.take_along_axis(idv[rev_t], cur_beam.astype(jnp.int32),
                                        axis=1)
            next_beam = jnp.take_along_axis(parv[rev_t],
                                            cur_beam.astype(jnp.int32), axis=1)
            return next_beam, out_t

        _, outs = lax.scan(step, beam0, jnp.arange(T))
        return outs[::-1]  # scan produced reversed time order

    return call_op("gather_tree", fn, (ids, parents))
