"""nn.Layer — module base with parameter/sublayer registration.

Reference analog: python/paddle/fluid/dygraph/layers.py (class Layer):
parameter/buffer/sublayer dicts, forward hooks, state_dict/set_state_dict,
train/eval, apply, to. TPU-first: parameters are jax-backed Parameter tensors;
`parameters_pytree()` exposes them as a pytree for jitted functional steps.
"""
from __future__ import annotations

import itertools
from collections import OrderedDict

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor, Parameter
from ..framework.dtype import to_jax_dtype, get_default_dtype
from ..framework import random as _random

__all__ = ["Layer"]

_layer_name_counter = itertools.count()


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        self.training = True
        self._dtype = dtype or get_default_dtype()
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names_set = set()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._next_hook_id = 0  # plain int: keeps Layer deepcopy-able
        if name_scope is None:
            name_scope = self.__class__.__name__.lower()
        self._full_name = f"{name_scope}_{next(_layer_name_counter)}"

    # -- registration -------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError(
                    "call super().__init__() before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError(
                    "call super().__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, None)
                    return
            if layers is not None and name in layers:
                if value is None:
                    layers.pop(name)
                    object.__setattr__(self, name, None)
                    return
            if buffers is not None and name in buffers and isinstance(value, Tensor):
                buffers[name] = value
                return
            object.__setattr__(self, name, value)
            return
        # also set as plain attribute for fast access
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
            object.__setattr__(self, name, parameter)
        return parameter

    def add_sublayer(self, name, sublayer):
        name = str(name)
        self._sub_layers[name] = sublayer
        if name.isidentifier():
            object.__setattr__(self, name, sublayer)
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names_set.add(name)
        if name.isidentifier():
            object.__setattr__(self, name, tensor)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .initializer_util import materialize_parameter
        return materialize_parameter(shape, attr=attr,
                                     dtype=dtype or self._dtype,
                                     is_bias=is_bias,
                                     default_initializer=default_initializer)

    def create_tensor(self, name=None, persistable=False, dtype=None):
        return Tensor(jnp.zeros([0], to_jax_dtype(dtype or self._dtype)),
                      name=name)

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + "." + lname if prefix else lname
                for n, p in layer.named_parameters(prefix=sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + "." + name if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + "." + lname if prefix else lname
                yield from layer.named_buffers(prefix=sub_prefix)

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for layer in self._sub_layers.values():
            if layer is not None:
                out.extend(layer.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from layer.named_sublayers(prefix=sub_prefix,
                                             include_self=True,
                                             layers_set=layers_set)

    def children(self):
        return (l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return ((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # -- mode ---------------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.children():
            layer.train()
        return self

    def eval(self):
        self.training = False
        for layer in self.children():
            layer.eval()
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        hid = self._next_hook_id
        self._next_hook_id += 1
        self._forward_pre_hooks[hid] = hook
        return HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = self._next_hook_id
        self._next_hook_id += 1
        self._forward_post_hooks[hid] = hook
        return HookRemoveHelper(self._forward_post_hooks, hid)

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers():
            short = name.rsplit(".", 1)[-1]
            if short not in self._non_persistable_buffer_names_set:
                dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for key, value in state_dict.items():
            if key in own:
                target = own[key]
                v = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
                if list(v.shape) != target.shape:
                    raise ValueError(
                        f"shape mismatch for {key}: got {list(v.shape)}, "
                        f"expected {target.shape}")
                target._value = jnp.asarray(v, target._value.dtype)
                matched.add(key)
            else:
                unexpected.append(key)
        missing = [k for k in own if k not in matched]
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device -----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            jd = to_jax_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._value = p._value.astype(jd)
            for b in self.buffers():
                if jnp.issubdtype(b._value.dtype, jnp.floating):
                    b._value = b._value.astype(jd)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            body = "\n".join("  " + l for l in rep)
            lines.append(f"({name}): {body.lstrip()}")
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n" + "\n".join("  " + l for l in lines) + "\n"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- functional bridge (TPU-first) --------------------------------------
    def parameters_pytree(self):
        """Return (names, values) of all parameters+persistable buffers as a
        flat pytree for jitted functional training steps."""
        names, values = [], []
        for n, p in self.named_parameters():
            names.append(n)
            values.append(p._value)
        return names, values

    def load_pytree(self, names, values):
        lookup = dict(zip(names, values))
        for n, p in self.named_parameters():
            if n in lookup:
                p._value = lookup[n]
