"""Common layers. Reference analog: python/paddle/nn/layer/common.py."""
from __future__ import annotations

import jax.numpy as jnp

from ..layer_base import Layer
from ..initializer_util import ParamAttr, materialize_parameter
from .. import initializer as I
from .. import functional as F
from ...framework.core import Tensor
from ...ops import manipulation as manip

__all__ = ["Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
           "Embedding", "Flatten", "Identity", "Upsample", "UpsamplingBilinear2D",
           "UpsamplingNearest2D", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
           "CosineSimilarity", "PixelShuffle", "PixelUnshuffle",
           "ChannelShuffle", "Bilinear", "Unfold", "Fold", "MaxUnPool2D"]


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (paddle layout).

    Reference: python/paddle/nn/layer/common.py Linear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = materialize_parameter(
            [in_features, out_features], attr=weight_attr, dtype=self._dtype,
            default_initializer=I.XavierNormal())
        self.bias = materialize_parameter(
            [out_features], attr=bias_attr, dtype=self._dtype, is_bias=True)

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, input):
        return F.dropout(input, p=self.p, axis=self.axis,
                         training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout2d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout3d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, p=self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx if padding_idx is None or \
            padding_idx >= 0 else num_embeddings + padding_idx
        self.weight = materialize_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            dtype=self._dtype, default_initializer=I.Normal(0.0, 1.0))
        if self._padding_idx is not None:
            self.weight._value = self.weight._value.at[self._padding_idx].set(0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, input):
        return manip.flatten(input, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, input):
        return input


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return manip.pad(x, self.padding, self.mode, self.value,
                         self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = materialize_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            dtype=self._dtype, default_initializer=I.XavierNormal(
                fan_in=in1_features, fan_out=in2_features))
        self.bias = materialize_parameter([out_features], attr=bias_attr,
                                          dtype=self._dtype, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        from ...ops._helpers import ensure_tensor, call_op, const_input
        x = ensure_tensor(x)
        idx = const_input(indices)
        ks = self.kernel_size if isinstance(self.kernel_size, (list, tuple)) \
            else (self.kernel_size, self.kernel_size)
        st = self.stride if isinstance(self.stride, (list, tuple)) \
            else (self.stride, self.stride)
        n, c, h, w = x.shape
        oh = (h - 1) * st[0] + ks[0] - 2 * self.padding
        ow = (w - 1) * st[1] + ks[1] - 2 * self.padding
        if self.output_size is not None:
            oh, ow = self.output_size[-2], self.output_size[-1]

        def fn(v, iv):
            flat = v.reshape(n, c, -1)
            out = jnp.zeros((n, c, oh * ow), v.dtype)
            iflat = iv.reshape(n, c, -1)
            bidx = jnp.arange(n)[:, None, None]
            cidx = jnp.arange(c)[None, :, None]
            out = out.at[bidx, cidx, iflat].set(flat)
            return out.reshape(n, c, oh, ow)
        return call_op("max_unpool2d", fn, (x, idx))


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format,
                              self.output_size)


class PairwiseDistance(Layer):
    """p-norm of (x - y + epsilon) along the last dim (reference:
    python/paddle/nn/layer/distance.py)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        from ...ops._helpers import call_op, ensure_tensor as _et
        def fn(a, b):
            d = a - b + self.epsilon
            return jnp.sum(jnp.abs(d) ** self.p, axis=-1,
                           keepdims=self.keepdim) ** (1.0 / self.p)
        return call_op("pairwise_distance", fn, (_et(x), _et(y)))


__all__ += ["MaxUnPool1D", "MaxUnPool3D", "PairwiseDistance"]
