"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference analog: python/paddle/nn/layer/rnn.py (the fluid
layers/rnn.py BeamSearchDecoder/dynamic_decode pair re-exported by
paddle.nn). TPU-first note: the decode loop here is the eager/dygraph
path (host loop, mirrors the reference's dygraph branch); the
compiled serving path for generation is `model.generate()`-style
lax.scan decode in models (see models/gpt.py) — this API exists for
seq2seq parity (attention/RNN cells, beam backtrace via gather_tree).
"""
from __future__ import annotations

from collections import namedtuple

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ..layer_base import Layer
from ...ops._helpers import ensure_tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode", "Decoder"]


class Decoder:
    """Abstract decoder API: initialize / step / finalize
    (reference: fluid/layers/rnn.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


BeamSearchState = namedtuple("BeamSearchState",
                             ["cell_states", "log_probs", "finished",
                              "lengths"])
BeamSearchOutput = namedtuple("BeamSearchOutput",
                              ["scores", "predicted_ids", "parent_ids"])


def _map_structure(fn, obj):
    if isinstance(obj, (list, tuple)):
        return type(obj)(_map_structure(fn, o) for o in obj)
    return fn(obj)


class BeamSearchDecoder(Decoder):
    """Beam search over an RNNCell. reference:
    python/paddle/fluid/layers/rnn.py BeamSearchDecoder."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.kinf = 1e9

    # -- beam/batch merge helpers (reference: _merge_batch_beams etc.) --
    def _merge(self, x):
        v = ensure_tensor(x)._value
        return Tensor(v.reshape((-1,) + v.shape[2:]))

    def _split(self, x):
        v = ensure_tensor(x)._value
        return Tensor(v.reshape((-1, self.beam_size) + v.shape[1:]))

    def _tile_beam(self, x):
        v = ensure_tensor(x)._value
        v = jnp.repeat(v[:, None], self.beam_size, axis=1)
        return Tensor(v)

    def initialize(self, initial_cell_states):
        states = _map_structure(self._tile_beam, initial_cell_states)
        batch = ensure_tensor(
            states[0] if isinstance(states, (list, tuple)) else states
        )._value.shape[0]
        # beam 0 active, others -inf so the first step picks distinct tokens
        log_probs = jnp.tile(
            jnp.array([0.0] + [-self.kinf] * (self.beam_size - 1),
                      jnp.float32), (batch, 1))
        init_ids = jnp.full((batch, self.beam_size), self.start_token,
                            jnp.int64)
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int64)
        state = BeamSearchState(states, Tensor(log_probs), Tensor(finished),
                                Tensor(lengths))
        return Tensor(init_ids), state, Tensor(finished)

    def step(self, time, inputs, states, **kwargs):
        cell_states = states.cell_states
        inp = inputs
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        merged_inp = self._merge(inp)
        merged_states = _map_structure(self._merge, cell_states)
        cell_out, next_cell_states = self.cell(merged_inp, merged_states,
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = self._split(cell_out)._value.astype(jnp.float32)
        B, K, V = logits.shape

        step_log_probs = jax.nn.log_softmax(logits, axis=-1)
        fin = states.finished._value
        # finished beams only extend with end_token at probability 1
        noend_mask = jnp.full((V,), -self.kinf).at[self.end_token].set(0.0)
        step_log_probs = jnp.where(fin[..., None], noend_mask[None, None],
                                   step_log_probs)
        log_probs = states.log_probs._value[..., None] + step_log_probs
        flat = log_probs.reshape(B, K * V)
        topk_lp, topk_idx = jax.lax.top_k(flat, K)
        parent = (topk_idx // V).astype(jnp.int64)
        token = (topk_idx % V).astype(jnp.int64)

        def gather_beam(x):
            v = self._split(x)._value
            return Tensor(jnp.take_along_axis(
                v, parent.reshape((B, K) + (1,) * (v.ndim - 2)), axis=1))

        next_cell_states = _map_structure(
            lambda s: gather_beam(s), next_cell_states)
        prev_fin = jnp.take_along_axis(fin, parent, axis=1)
        next_fin = prev_fin | (token == self.end_token)
        prev_len = jnp.take_along_axis(states.lengths._value, parent, axis=1)
        next_len = prev_len + (~prev_fin).astype(jnp.int64)

        beam_state = BeamSearchState(next_cell_states, Tensor(topk_lp),
                                     Tensor(next_fin), Tensor(next_len))
        output = BeamSearchOutput(Tensor(topk_lp), Tensor(token),
                                  Tensor(parent))
        return output, beam_state, Tensor(token), Tensor(next_fin)

    def finalize(self, outputs, final_states, sequence_lengths):
        from ..functional.sequence import gather_tree
        ids = gather_tree(outputs.predicted_ids, outputs.parent_ids)
        return ids, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run `decoder` until every sequence finishes or `max_step_num`.
    Returns (outputs, final_states[, sequence_lengths]) like the
    reference (fluid/layers/rnn.py dynamic_decode dygraph branch)."""
    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    time = 0
    limit = int(max_step_num) if max_step_num is not None else 10 ** 9
    while time < limit:
        out, states, inputs, finished = decoder.step(time, inputs, states,
                                                     **kwargs)
        step_outputs.append(out)
        time += 1
        if bool(np.asarray(ensure_tensor(finished)._value).all()):
            break

    def stack_field(i):
        return Tensor(jnp.stack(
            [ensure_tensor(o[i])._value for o in step_outputs]))

    if isinstance(step_outputs[0], tuple):
        outputs = type(step_outputs[0])(
            *[stack_field(i) for i in range(len(step_outputs[0]))])
    else:
        outputs = stack_field(0)

    seq_len = getattr(states, "lengths", None)
    final_outputs, final_states = decoder.finalize(outputs, states, seq_len)

    if not output_time_major:
        def to_batch_major(t):
            v = ensure_tensor(t)._value
            return Tensor(jnp.swapaxes(v, 0, 1))
        final_outputs = _map_structure(to_batch_major, final_outputs)

    if return_length:
        return final_outputs, final_states, seq_len
    return final_outputs, final_states
