"""Norm layers. Reference analog: python/paddle/nn/layer/norm.py."""
from __future__ import annotations

import jax.numpy as jnp

from ..layer_base import Layer
from ..initializer_util import materialize_parameter
from .. import initializer as I
from .. import functional as F
from ...framework.core import Tensor

__all__ = ["LayerNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "RMSNorm",
           "SpectralNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = materialize_parameter(
            self._normalized_shape, attr=weight_attr, dtype=self._dtype,
            default_initializer=I.Constant(1.0))
        self.bias = materialize_parameter(
            self._normalized_shape, attr=bias_attr, dtype=self._dtype,
            is_bias=True)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = materialize_parameter(
            [hidden_size], attr=weight_attr, dtype=self._dtype,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = materialize_parameter(
            [num_features], attr=weight_attr, dtype=self._dtype,
            default_initializer=I.Constant(1.0))
        self.bias = materialize_parameter(
            [num_features], attr=bias_attr, dtype=self._dtype, is_bias=True)
        self._mean = Tensor(jnp.zeros([num_features], jnp.float32),
                            persistable=True)
        self._variance = Tensor(jnp.ones([num_features], jnp.float32),
                                persistable=True)
        self.register_buffer("_mean", self._mean)
        self.register_buffer("_variance", self._variance)

    def forward(self, input):
        return F.batch_norm(input, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    Reference analog: python/paddle/nn/layer/norm.py SyncBatchNorm over
    sync_batch_norm_op. TPU-first: under pjit/shard_map the batch axis is a
    mesh axis; stats sync happens automatically via psum when traced inside
    shard_map. In eager single-process mode it behaves like BatchNorm.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            converted = cls.convert_sync_batchnorm(sub)
            if converted is not sub:
                out.add_sublayer(name, converted)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = materialize_parameter(
            [num_channels], attr=weight_attr, dtype=self._dtype,
            default_initializer=I.Constant(1.0))
        self.bias = materialize_parameter(
            [num_channels], attr=bias_attr, dtype=self._dtype, is_bias=True)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon,
                            self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = materialize_parameter(
                [num_features], attr=weight_attr, dtype=self._dtype,
                default_initializer=I.Constant(1.0))
            self.bias = materialize_parameter(
                [num_features], attr=bias_attr, dtype=self._dtype, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral normalization of a weight tensor via power iteration.
    Reference: python/paddle/nn/layer/norm.py SpectralNorm (spectral_norm op)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = materialize_parameter(
            [h], dtype=dtype, default_initializer=I.Normal(0.0, 1.0))
        self.weight_v = materialize_parameter(
            [w], dtype=dtype, default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, x):
        from ...ops._helpers import ensure_tensor, call_op, const_input
        x = ensure_tensor(x)
        dim = self._dim
        u_t, v_t = self.weight_u, self.weight_v

        # power iteration outside the grad graph
        wm = jnp.moveaxis(x._value, dim, 0).reshape(x.shape[dim], -1) \
            .astype(jnp.float32)
        u = u_t._value
        v = v_t._value
        for _ in range(self._power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + self._eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + self._eps)
        u_t._value = u
        v_t._value = v

        # the iterated u/v ride as dispatch inputs: they change every
        # call, so a closure capture would re-key the op forever
        def fn(w, uu, vv):
            wmat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            sigma = uu @ (wmat.astype(jnp.float32) @ vv)
            return w / sigma.astype(w.dtype)
        return call_op("spectral_norm", fn,
                       (x, const_input(u), const_input(v)))
