"""Pooling layers. Reference analog: python/paddle/nn/layer/pooling.py."""
from __future__ import annotations

from ..layer_base import Layer
from .. import functional as F

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _Pool(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, exclusive=True, divisor_override=None,
                 data_format=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.return_mask = return_mask
        self.exclusive = exclusive
        self.divisor_override = divisor_override
        self.data_format = data_format

    def extra_repr(self):
        return f"kernel_size={self.kernel_size}, stride={self.stride}"


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.exclusive, self.ceil_mode)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            self.divisor_override,
                            self.data_format or "NCHW")


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.ceil_mode, self.exclusive,
                            self.divisor_override,
                            self.data_format or "NCDHW")


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode,
                            self.data_format or "NCHW")


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.return_mask, self.ceil_mode,
                            self.data_format or "NCDHW")


class _AdaptivePool(Layer):
    def __init__(self, output_size, return_mask=False, data_format=None,
                 name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask
        self.data_format = data_format


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     self.data_format or "NCHW")


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size,
                                     self.data_format or "NCDHW")


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)
