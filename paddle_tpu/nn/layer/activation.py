"""Activation layers. Reference analog: python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from ..layer_base import Layer
from ..initializer_util import materialize_parameter
from .. import initializer as I
from .. import functional as F

__all__ = ["ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax", "LogSoftmax",
           "Silu", "Swish", "Hardswish", "Hardsigmoid", "LeakyReLU", "ELU",
           "CELU", "SELU", "PReLU", "Softplus", "Softsign", "Hardtanh",
           "Mish", "Tanhshrink", "Hardshrink", "Softshrink", "GLU", "Maxout",
           "ThresholdedReLU", "LogSigmoid", "RReLU"]


def _simple(fname, cls_name, **defaults):
    fn = getattr(F, fname)

    class _Act(Layer):
        def __init__(self, name=None, **kwargs):
            super().__init__()
            self._kwargs = {**defaults, **kwargs}

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = cls_name
    _Act.__qualname__ = cls_name
    return _Act


ReLU = _simple("relu", "ReLU")
ReLU6 = _simple("relu6", "ReLU6")
Sigmoid = _simple("sigmoid", "Sigmoid")
Tanh = _simple("tanh", "Tanh")
Silu = _simple("silu", "Silu")
Swish = _simple("swish", "Swish")
Hardswish = _simple("hardswish", "Hardswish")
Softsign = _simple("softsign", "Softsign")
Mish = _simple("mish", "Mish")
Tanhshrink = _simple("tanhshrink", "Tanhshrink")
LogSigmoid = _simple("log_sigmoid", "LogSigmoid")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, self._approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, self._axis)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self._scale = scale
        self._alpha = alpha

    def forward(self, x):
        return F.selu(x, self._scale, self._alpha)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = materialize_parameter(
            [num_parameters], attr=weight_attr, dtype=self._dtype,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Softplus(Layer):
    def __init__(self, beta=1, threshold=20, name=None):
        super().__init__()
        self._beta = beta
        self._threshold = threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min = min
        self._max = max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self._threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self._threshold)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups = groups
        self._axis = axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self._threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self._lower = lower
        self._upper = upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, self.training)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input (reference:
    python/paddle/nn/layer/activation.py Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        from .. import functional as F
        assert len(x.shape) in (3, 4), "Softmax2D expects 3D/4D input"
        return F.softmax(x, axis=-3)


__all__ += ["Softmax2D"]
