"""Recurrent layers over lax.scan.

Reference analog: python/paddle/nn/layer/rnn.py (SimpleRNN/LSTM/GRU over the
cudnn rnn op / rnn_op). TPU-first: the time loop is a single `lax.scan`
(compiler-friendly static control flow), gates are fused matmuls.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..layer_base import Layer
from ..initializer_util import materialize_parameter
from .. import initializer as I
from ...framework.core import Tensor
from ...ops._helpers import ensure_tensor, call_op

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN", "LSTM",
           "GRU", "BiRNN"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        state_shape = [batch, self.hidden_size]
        from ...ops.creation import full
        return full(state_shape, init_value, dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = materialize_parameter([hidden_size, input_size],
                                               weight_ih_attr, self._dtype,
                                               default_initializer=u)
        self.weight_hh = materialize_parameter([hidden_size, hidden_size],
                                               weight_hh_attr, self._dtype,
                                               default_initializer=u)
        self.bias_ih = materialize_parameter([hidden_size], bias_ih_attr,
                                             self._dtype, is_bias=True,
                                             default_initializer=u)
        self.bias_hh = materialize_parameter([hidden_size], bias_hh_attr,
                                             self._dtype, is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else \
            (lambda v: jnp.maximum(v, 0))

        def fn(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = call_op("simple_rnn_cell", fn,
                    (ensure_tensor(inputs), ensure_tensor(states),
                     self.weight_ih, self.weight_hh, self.bias_ih,
                     self.bias_hh))
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = materialize_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr, self._dtype,
                                               default_initializer=u)
        self.weight_hh = materialize_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr, self._dtype,
                                               default_initializer=u)
        self.bias_ih = materialize_parameter([4 * hidden_size], bias_ih_attr,
                                             self._dtype, is_bias=True,
                                             default_initializer=u)
        self.bias_hh = materialize_parameter([4 * hidden_size], bias_hh_attr,
                                             self._dtype, is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
            states = (h, c)
        h_prev, c_prev = states

        def fn(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        from ...ops._helpers import call_op_multi
        h, c = call_op_multi("lstm_cell", fn,
                             (ensure_tensor(inputs), ensure_tensor(h_prev),
                              ensure_tensor(c_prev), self.weight_ih,
                              self.weight_hh, self.bias_ih, self.bias_hh), 2)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = materialize_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr, self._dtype,
                                               default_initializer=u)
        self.weight_hh = materialize_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr, self._dtype,
                                               default_initializer=u)
        self.bias_ih = materialize_parameter([3 * hidden_size], bias_ih_attr,
                                             self._dtype, is_bias=True,
                                             default_initializer=u)
        self.bias_hh = materialize_parameter([3 * hidden_size], bias_hh_attr,
                                             self._dtype, is_bias=True,
                                             default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h
        h = call_op("gru_cell", fn,
                    (ensure_tensor(inputs), ensure_tensor(states),
                     self.weight_ih, self.weight_hh, self.bias_ih,
                     self.bias_hh))
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)


class RNN(Layer):
    """Run a cell over time with lax.scan. Reference: nn/layer/rnn.py RNN."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # eager loop keeping the cell abstraction (the multi-layer wrappers
        # below use the fused scan path)
        inputs = ensure_tensor(inputs)
        axis = 0 if self.time_major else 1
        steps = inputs.shape[axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        outs = []
        states = initial_states
        from ...ops.manipulation import stack, unbind
        xs = unbind(inputs, axis)
        for t in order:
            out, states = self.cell(xs[t], states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, fw_states = self.rnn_fw(inputs, st_fw)
        out_bw, bw_states = self.rnn_bw(inputs, st_bw)
        from ...ops.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (fw_states, bw_states)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) recurrent net with a fused
    lax.scan over time per layer/direction."""

    MODE = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirect else 1
        gate_mult = {"RNN_TANH": 1, "RNN_RELU": 1, "LSTM": 4, "GRU": 3}[self.MODE]
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                suffix = "_reverse" if d == 1 else ""
                wi = materialize_parameter([gate_mult * hidden_size, in_sz],
                                           weight_ih_attr, self._dtype,
                                           default_initializer=u)
                wh = materialize_parameter(
                    [gate_mult * hidden_size, hidden_size], weight_hh_attr,
                    self._dtype, default_initializer=u)
                bi = materialize_parameter([gate_mult * hidden_size],
                                           bias_ih_attr, self._dtype,
                                           is_bias=True, default_initializer=u)
                bh = materialize_parameter([gate_mult * hidden_size],
                                           bias_hh_attr, self._dtype,
                                           is_bias=True, default_initializer=u)
                self.add_parameter(f"weight_ih_l{layer}{suffix}", wi)
                self.add_parameter(f"weight_hh_l{layer}{suffix}", wh)
                self.add_parameter(f"bias_ih_l{layer}{suffix}", bi)
                self.add_parameter(f"bias_hh_l{layer}{suffix}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def _cell_step(self, mode):
        if mode == "LSTM":
            def step(carry, x, wi, wh, bi, bh):
                h, c = carry
                gates = x @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c_new = f * c + i * g
                h_new = o * jnp.tanh(c_new)
                return (h_new, c_new), h_new
        elif mode == "GRU":
            def step(carry, x, wi, wh, bi, bh):
                h = carry[0]
                xg = x @ wi.T + bi
                hg = h @ wh.T + bh
                xr, xz, xn = jnp.split(xg, 3, axis=-1)
                hr, hz, hn = jnp.split(hg, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                h_new = (1 - z) * n + z * h
                return (h_new,), h_new
        else:
            act = jnp.tanh if mode == "RNN_TANH" else \
                (lambda v: jnp.maximum(v, 0))

            def step(carry, x, wi, wh, bi, bh):
                h = carry[0]
                h_new = act(x @ wi.T + bi + h @ wh.T + bh)
                return (h_new,), h_new
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = ensure_tensor(inputs)
        num_dirs = 2 if self.bidirect else 1
        mode = self.MODE
        step = self._cell_step(mode)
        is_lstm = mode == "LSTM"
        time_major = self.time_major
        num_layers = self.num_layers
        hidden = self.hidden_size

        flat_weights = [w for group in self._all_weights for w in group]

        def fn(x, *weights):
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # -> [T, B, C]
            batch = x.shape[1]
            h_states = []
            c_states = []
            out = x
            wi_idx = 0
            for layer in range(num_layers):
                dir_outs = []
                for d in range(num_dirs):
                    wi, wh, bi, bh = weights[wi_idx:wi_idx + 4]
                    wi_idx += 4
                    h0 = jnp.zeros((batch, hidden), x.dtype)
                    carry = (h0, jnp.zeros((batch, hidden), x.dtype)) \
                        if is_lstm else (h0,)
                    seq = jnp.flip(out, 0) if d == 1 else out

                    def scan_fn(c, xt, _wi=wi, _wh=wh, _bi=bi, _bh=bh):
                        return step(c, xt, _wi, _wh, _bi, _bh)
                    final, ys = jax.lax.scan(scan_fn, carry, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                    h_states.append(final[0])
                    if is_lstm:
                        c_states.append(final[1])
                out = jnp.concatenate(dir_outs, axis=-1) if num_dirs == 2 \
                    else dir_outs[0]
            h_all = jnp.stack(h_states)  # [L*D, B, H]
            if not time_major:
                out = jnp.swapaxes(out, 0, 1)
            if is_lstm:
                return out, h_all, jnp.stack(c_states)
            return out, h_all

        from ...ops._helpers import call_op_multi
        n_out = 3 if is_lstm else 2
        outs = call_op_multi(f"rnn_{mode.lower()}", fn,
                             tuple([inputs] + flat_weights), n_out)
        if is_lstm:
            return outs[0], (outs[1], outs[2])
        return outs[0], outs[1]


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        self.MODE = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"
