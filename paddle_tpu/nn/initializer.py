"""Weight initializers. Reference analog: python/paddle/nn/initializer/ backed
by fill/gaussian/uniform kernels; fan computation mirrors
python/paddle/fluid/initializer.py."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.random import get_rng_key

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Orthogonal", "Dirac", "calculate_gain"]


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4.0}
    return gains[nonlinearity]


def _compute_fans(shape):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean = mean
        self.std = std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.normal(
            get_rng_key(), shape, jnp.float32).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean = mean
        self.std = std

    def __call__(self, shape, dtype):
        return (self.mean + self.std * jax.random.truncated_normal(
            get_rng_key(), -2.0, 2.0, shape, jnp.float32)).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low = low
        self.high = high

    def __call__(self, shape, dtype):
        return jax.random.uniform(get_rng_key(), shape, jnp.float32,
                                  self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in = fan_in
        self._fan_out = fan_out
        self.gain = gain

    def __call__(self, shape, dtype):
        fi, fo = _compute_fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(get_rng_key(), shape,
                                       jnp.float32).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in = fan_in
        self._fan_out = fan_out
        self.gain = gain

    def __call__(self, shape, dtype):
        fi, fo = _compute_fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(get_rng_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _compute_fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(get_rng_key(), shape,
                                       jnp.float32).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _compute_fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(get_rng_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        return jnp.asarray(np.asarray(v), dtype).reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(get_rng_key(), (max(rows, cols),
                                                 min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, np.float32)
        out_c, in_c = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(out_c // self.groups, in_c)):
                idx = (g * (out_c // self.groups) + i, i, *centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype)


class Bilinear(Initializer):
    """Bilinear-interpolation kernel init for transposed-conv upsampling
    (reference: nn/initializer/Bilinear over bilinear_init): weight
    [C_out, C_in, kH, kW] gets the separable triangle kernel."""

    def __call__(self, shape, dtype):
        import numpy as np
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError(
                f"Bilinear initializer needs a 4-D conv weight, got {shape}")
        kh, kw = shape[2], shape[3]

        def tri(k):
            f = (k + 1) // 2
            c = f - 1 if k % 2 == 1 else f - 0.5
            return 1 - np.abs(np.arange(k) - c) / f

        kernel = np.outer(tri(kh), tri(kw)).astype(np.float32)
        w = np.zeros(shape, np.float32)
        for i in range(min(shape[0], shape[1])):
            w[i, i % shape[1]] = kernel
        return jnp.asarray(w, dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Set the DEFAULT initializers used when a parameter has no explicit
    one (reference: nn/initializer/set_global_initializer — applies to
    parameters created afterwards; pass None to reset)."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _global_initializer(is_bias):
    return _global_bias_init if is_bias else _global_weight_init


__all__ += ["Bilinear", "set_global_initializer"]
