"""paddle.nn.utils (reference: python/paddle/nn/utils — weight_norm /
spectral_norm reparameterizations + parameter/vector converters).

TPU-first reparameterization: instead of op-hooks on a mutable program,
the wrapped layer's forward recomputes the effective weight from the
reparam parameters each call — one extra fused normalize per step that XLA
folds into the matmul's producer chain."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...framework.core import Tensor, Parameter
from ...ops.dispatch import call_op

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt((v * v).sum(axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize `layer.name` as g * v/||v|| (reference
    nn/utils/weight_norm_hook.py). Registers `name`_g / `name`_v and
    recomputes the weight in a wrapped forward."""
    w = getattr(layer, name)
    v0 = w._value
    if dim is not None and dim < 0:
        dim = v0.ndim + dim             # dim=-1 means the LAST axis
    if dim is None:                      # None = whole-tensor norm
        g0 = jnp.sqrt((v0 * v0).sum())
    else:
        g0 = _norm_except(v0, dim).reshape(-1)
    g = Parameter(g0)
    g.stop_gradient = False
    v = Parameter(v0)
    v.stop_gradient = False
    setattr(layer, name + "_g", g)
    setattr(layer, name + "_v", v)

    orig_forward = layer.forward

    def _effective_weight():
        def fn(gv, vv):
            if dim is None:
                nrm = jnp.sqrt((vv * vv).sum())
                return vv * (gv / nrm)
            nrm = _norm_except(vv, dim)
            shape = [1] * vv.ndim
            shape[dim] = -1
            return vv / nrm * gv.reshape(shape)
        return call_op("weight_norm", fn, (g, v))

    def forward(*args, **kwargs):
        eff = _effective_weight()
        saved = getattr(layer, name)
        try:
            # swap the effective weight in: Parameter identity preserved
            saved_val = saved._value
            saved_node = saved._grad_node
            saved_idx = saved._out_index
            saved._value = eff._value
            saved._grad_node = eff._grad_node
            saved._out_index = eff._out_index
            return orig_forward(*args, **kwargs)
        finally:
            saved._value = saved_val
            saved._grad_node = saved_node
            saved._out_index = saved_idx

    layer.forward = forward
    layer._weight_norm_info = (name, dim, orig_forward)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Bake the current effective weight back and restore the plain
    forward (reference remove_weight_norm)."""
    info = getattr(layer, "_weight_norm_info", None)
    if info is None:
        raise ValueError("layer has no weight_norm applied")
    pname, dim, orig_forward = info
    g = getattr(layer, pname + "_g")._value
    v = getattr(layer, pname + "_v")._value
    if dim is None:
        eff = v * (g / jnp.sqrt((v * v).sum()))
    else:
        shape = [1] * v.ndim
        shape[dim] = -1
        eff = v / _norm_except(v, dim) * g.reshape(shape)
    getattr(layer, pname)._value = eff
    layer.forward = orig_forward
    delattr(layer, pname + "_g")
    delattr(layer, pname + "_v")
    del layer._weight_norm_info
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral normalization of `layer.name` (reference
    nn/utils/spectral_norm_hook.py): the forward divides the weight by its
    leading singular value, estimated by persistent power iteration."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    v0 = w._value
    perm = [dim] + [i for i in range(v0.ndim) if i != dim]
    mat0 = jnp.transpose(v0, perm).reshape(v0.shape[dim], -1)
    rng = np.random.default_rng(0)
    layer._sn_u = jnp.asarray(rng.normal(size=(mat0.shape[0],)),
                              jnp.float32)
    orig_forward = layer.forward

    def forward(*args, **kwargs):
        saved = getattr(layer, name)
        saved_val = saved._value
        mat = jnp.transpose(saved_val, perm).reshape(saved_val.shape[dim],
                                                     -1)
        u = layer._sn_u
        for _ in range(max(int(n_power_iterations), 1)):
            vv = mat.T @ u
            vv = vv / (jnp.linalg.norm(vv) + eps)
            u = mat @ vv
            u = u / (jnp.linalg.norm(u) + eps)
        layer._sn_u = u                    # persistent estimate
        sigma = u @ mat @ vv
        try:
            saved._value = saved_val / sigma
            return orig_forward(*args, **kwargs)
        finally:
            saved._value = saved_val

    layer.forward = forward
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten parameters into one 1-D Tensor (reference
    nn/utils/transform_parameters.py)."""
    vals = [jnp.ravel(p._value) for p in parameters]
    return Tensor(jnp.concatenate(vals) if vals
                  else jnp.zeros((0,), jnp.float32))


def vector_to_parameters(vec, parameters, name=None):
    """Inverse of parameters_to_vector: writes slices back in order."""
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p._value.shape)) if p._value.ndim else 1
        p._value = v[off:off + n].reshape(p._value.shape) \
            .astype(p._value.dtype)
        off += n
    if off != v.shape[0]:
        raise ValueError(
            f"vector has {v.shape[0]} elements but parameters take {off}")
