"""Shared AST machinery for the fusion linter.

The rules (paddle_tpu/analysis/rules/) need four capabilities beyond a
raw `ast.walk`:

  * project loading — the default scan set is the package source plus
    tools/ and bench.py (never tests/, never fixtures), each file parsed
    once and shared across rules;
  * scope/closure resolution — for a `fn` passed into the dispatch
    funnel, which names does it CAPTURE from the enclosing op wrapper
    (free variables), as opposed to binding locally?
  * a light taint pass — is a captured name a Tensor/array (would make
    the op un-keyable) or a scalar/shape (keys by value)? Classified
    from the assignment forms the op corpus actually uses
    (`ensure_tensor(x)`, `x._value`, `jnp.asarray(...)`,
    `jax.random.*`), deliberately conservative: an UNKNOWN name is never
    flagged — the linter's false-positive budget is spent in the
    baseline file, not in the rules;
  * dispatch call-site discovery — every `call_op` / `call_op_multi` /
    `unary` / `binary` / `nary` call, with the fn expression resolved to
    its local def/lambda and the dispatch-input names collected.

Findings are plain records; reason codes come from the SAME public
REASON_CODES contract the flight recorder emits (profiler/events.py), so
the doctor can cross-reference a runtime split with the static finding
that predicted it.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

__all__ = ["Finding", "ModuleInfo", "Project", "load_project", "run_rules",
           "RULE_DOCS", "FuncIndex", "free_loads", "bound_names",
           "TaintPass", "DispatchSite", "dispatch_sites", "qualname_of",
           "decorator_op_name", "parent_map", "enclosing_function"]


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Finding:
    """One rule violation. `symbol` is the enclosing function qualname —
    the stable baseline key (line numbers drift with every edit above
    them; a suppression pinned to (rule, file, symbol) survives)."""

    rule: str            # "R1".."R6"
    file: str            # repo-relative posix path
    line: int            # 1-indexed
    reason_code: str     # a REASON_CODES entry (profiler/events.py)
    message: str         # one-line, names the offending construct
    symbol: str = ""     # enclosing function qualname ("" = module level)

    def sort_key(self):
        return (self.file, self.line, self.rule, self.message)


# one-line rule documentation, keyed by rule id — report.py renders the
# table, README mirrors it
RULE_DOCS: dict = {}


# ---------------------------------------------------------------------------
# project loading
# ---------------------------------------------------------------------------

@dataclass
class ModuleInfo:
    path: str                      # absolute
    rel: str                       # repo-relative posix path
    source: str
    tree: ast.Module
    _parents: dict = field(default=None, repr=False)

    def parents(self):
        """node -> parent map (built lazily, shared across rules)."""
        if self._parents is None:
            self._parents = parent_map(self.tree)
        return self._parents


@dataclass
class Project:
    root: str
    modules: list                  # [ModuleInfo]

    def module(self, rel):
        for m in self.modules:
            if m.rel == rel:
                return m
        return None

    def parse_errors(self):
        """[(rel, error)] for files the loader could not parse. An
        unparsable file contributes zero findings to every rule — the
        CLI treats any entry here as a hard error (exit 2), because the
        file most likely to be broken is exactly the one a silent skip
        would stop covering."""
        return [(m.rel, m.parse_error) for m in self.modules
                if getattr(m, "parse_error", None)]


_DEFAULT_SCAN = ("paddle_tpu", "tools", "bench.py")
_SKIP_DIRS = {"__pycache__", "tests", "bench_traces", ".git"}


def _repo_root():
    """The checkout root: two levels above this file
    (paddle_tpu/analysis/analyzer.py)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _iter_py(base):
    if os.path.isfile(base):
        if base.endswith(".py"):
            yield base
        return
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def load_project(root=None, paths=None):
    """Parse the scan set once. `paths` (files or directories, absolute
    or root-relative) overrides the default package+tools set — that is
    how the golden known-bad fixtures run through the same pipeline.
    An EXPLICIT path that does not exist raises: a typo'd CI wiring
    must fail loudly, never scan nothing and report the tree clean."""
    root = os.path.abspath(root or _repo_root())
    bases = []
    explicit = paths is not None and len(paths) > 0
    for p in (paths if explicit else _DEFAULT_SCAN):
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.exists(ap):
            bases.append(ap)
        elif explicit:
            raise FileNotFoundError(
                f"fusion_lint: scan path does not exist: {ap}")
    modules = []
    for base in bases:
        for path in _iter_py(base):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
                tree = ast.parse(src, filename=path)
            except (OSError, SyntaxError) as e:
                # an unparsable file is itself a finding-worthy event,
                # but the linter must never crash on one
                modules.append(ModuleInfo(
                    path=path, rel=_rel(path, root),
                    source="", tree=ast.parse("")))
                modules[-1].parse_error = str(e)
                continue
            modules.append(ModuleInfo(path=path, rel=_rel(path, root),
                                      source=src, tree=tree))
    return Project(root=root, modules=modules)


def _rel(path, root):
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        rel = path
    return rel.replace(os.sep, "/")


def run_rules(project, rules=None):
    """Run the registered rule set over a loaded project; returns
    findings sorted by (file, line, rule). Unknown rule ids raise —
    `--rules R7` must not silently select nothing and pass the gate."""
    from .rules import RULES
    if rules is None:
        selected = RULES
    else:
        wanted = set(rules)
        unknown = wanted - {r.id for r in RULES}
        if unknown:
            raise ValueError(
                f"unknown rule id(s) {sorted(unknown)}; available: "
                f"{sorted(r.id for r in RULES)}")
        selected = [r for r in RULES if r.id in wanted]
    findings = []
    for r in selected:
        findings.extend(r.run(project))
    return sorted(set(findings), key=Finding.sort_key)


# ---------------------------------------------------------------------------
# AST utilities: parents, qualnames, decorators
# ---------------------------------------------------------------------------

def parent_map(tree):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def enclosing_function(node, parents):
    """Nearest enclosing def/lambda of `node`, or None at module level."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, _FUNC_NODES):
            return cur
        cur = parents.get(cur)
    return None


def qualname_of(node, parents):
    """Dotted def/class path of the scope containing `node` (for the
    baseline key)."""
    names = []
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names))


def decorator_op_name(funcdef):
    """The op name when `funcdef` is decorated `@register_op("name",
    ...)`, else None."""
    for dec in getattr(funcdef, "decorator_list", ()):
        if isinstance(dec, ast.Call):
            fn = dec.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name == "register_op" and dec.args and \
                    isinstance(dec.args[0], ast.Constant) and \
                    isinstance(dec.args[0].value, str):
                return dec.args[0].value
    return None


def call_name(call):
    """Terminal name of a Call's callee: `foo(...)` and `a.b.foo(...)`
    both answer "foo"."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def dotted_name(node):
    """"a.b.c" for an Attribute/Name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# scope resolution: bindings and free variables
# ---------------------------------------------------------------------------

def _collect_bound(node, acc):
    """Names bound anywhere inside `node` (params, assignments, loop and
    with targets, defs, imports, walrus) — including nested function
    scopes. Over-approximating the bound set errs toward FEWER captures,
    the safe direction for a linter."""
    if isinstance(node, _FUNC_NODES):
        a = node.args
        for arg in (list(a.posonlyargs) + list(a.args)
                    + list(a.kwonlyargs)
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            acc.add(arg.arg)
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.Name) and isinstance(
                child.ctx, (ast.Store, ast.Del)):
            acc.add(child.id)
        elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
            acc.add(child.name)
        elif isinstance(child, (ast.Import, ast.ImportFrom)):
            for alias in child.names:
                acc.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(child, ast.ExceptHandler) and child.name:
            acc.add(child.name)
        _collect_bound(child, acc)
    return acc


def bound_names(fn_node):
    """Every name bound within `fn_node` (its params + all inner
    bindings, nested scopes included)."""
    return _collect_bound(fn_node, set())


def free_loads(fn_node):
    """{name: first_lineno} of names READ inside `fn_node` that it does
    not bind — the closure captures (plus globals/builtins; the caller
    intersects with the enclosing scope's bindings to separate them)."""
    bound = bound_names(fn_node)
    out = {}
    body = fn_node.body if isinstance(fn_node.body, list) else [fn_node.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                    and node.id not in bound and node.id not in out:
                out[node.id] = node.lineno
    return out


# ---------------------------------------------------------------------------
# taint: which names hold Tensors / arrays?
# ---------------------------------------------------------------------------

# np/jnp constructors whose results are device/host ARRAYS (a captured
# array can never be value-keyed). Deliberately explicit — shape helpers
# (broadcast_shapes), dtype helpers etc. return keyable tuples/scalars.
_ARRAY_FNS = {
    "asarray", "array", "zeros", "ones", "empty", "full", "arange",
    "linspace", "eye", "tril", "triu", "concatenate", "stack", "where",
    "broadcast_to", "zeros_like", "ones_like", "full_like", "device_put",
}
_TENSOR_FNS = {"ensure_tensor", "to_tensor", "Tensor"}
_PROPAGATE_METHODS = {"astype", "reshape", "clone", "transpose", "detach",
                      "copy"}


class TaintPass:
    """Single forward pass over one function body classifying local
    names: "tensor" (a framework Tensor), "array" (a raw jax/numpy
    array), or absent (scalar/shape/unknown — never flagged). The
    classification follows the op-corpus idiom: `x = ensure_tensor(x)`
    proves x is a Tensor; `v = x._value` / `.numpy()` / `jnp.asarray(..)`
    / `jax.random.<sampler>(..)` produce arrays."""

    def __init__(self, fn_node):
        self.taints = {}
        body = fn_node.body if isinstance(fn_node.body, list) \
            else [fn_node.body]
        for stmt in body:
            self._visit_stmt(stmt)

    def of(self, name):
        return self.taints.get(name)

    # -- statements ---------------------------------------------------------
    def _visit_stmt(self, stmt):
        if isinstance(stmt, _FUNC_NODES + (ast.ClassDef,)):
            return                       # nested scope: not this frame
        if isinstance(stmt, ast.Assign):
            # tuple-to-tuple assignment taints elementwise:
            # `a, b = ensure_tensor(x), ensure_tensor(y)`
            if len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], (ast.Tuple, ast.List)) \
                    and isinstance(stmt.value, (ast.Tuple, ast.List)) \
                    and len(stmt.targets[0].elts) == len(stmt.value.elts):
                for el, val in zip(stmt.targets[0].elts, stmt.value.elts):
                    t = self.taint_of(val)
                    if t and isinstance(el, ast.Name):
                        self.taints[el.id] = t
                return
            t = self.taint_of(stmt.value)
            if t:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        self.taints[tgt.id] = t
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        for el in tgt.elts:
                            if isinstance(el, ast.Name):
                                self.taints[el.id] = t
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name):
            t = self.taint_of(stmt.value)
            if t:
                self.taints[stmt.target.id] = t
        elif isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.target, ast.Name):
            t = self.taint_of(stmt.value)
            if t:
                self.taints[stmt.target.id] = t
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.stmt,)):
                self._visit_stmt(child)

    # -- expressions --------------------------------------------------------
    def taint_of(self, node):
        if isinstance(node, ast.Name):
            return self.taints.get(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr == "_value":
                return "array"
            return None
        if isinstance(node, ast.Subscript):
            t = self.taint_of(node.value)
            return "array" if t else None
        if isinstance(node, ast.BinOp):
            return self.taint_of(node.left) or self.taint_of(node.right)
        if isinstance(node, ast.IfExp):
            return self.taint_of(node.body) or self.taint_of(node.orelse)
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _TENSOR_FNS:
                return "tensor"
            if name == "numpy":
                return "array"
            if name in _PROPAGATE_METHODS \
                    and isinstance(node.func, ast.Attribute):
                inner = self.taint_of(node.func.value)
                if name == "detach" and inner:
                    return "tensor"
                return inner
            dn = dotted_name(node.func) or ""
            head = dn.split(".")[0]
            if head in ("np", "numpy", "jnp") and name in _ARRAY_FNS:
                return "array"
            if dn.startswith(("jax.random.", "random_mod.")) \
                    and name not in ("key_data", "wrap_key_data",
                                     "split", "key", "PRNGKey"):
                # a sampler result (gumbel/uniform/normal/...) is a fresh
                # array; key plumbing stays un-tainted (keys are handled
                # by R2, not R1)
                return "array"
            if dn in ("jax.device_put",):
                return "array"
        return None


# ---------------------------------------------------------------------------
# dispatch call-site discovery
# ---------------------------------------------------------------------------

# funnel entry points (ops/dispatch.py + ops/_helpers.py): positional
# layout is (name, fn, *inputs-ish)
_DISPATCH_WRAPPERS = {"call_op", "call_op_multi", "unary", "binary", "nary"}


@dataclass
class DispatchSite:
    call: ast.Call                 # the call_op(...) node
    op_name: str                   # literal op name ("" if dynamic)
    fn_expr: ast.AST               # the fn argument expression
    fn_node: ast.AST               # resolved local def/lambda, or None
    input_names: set               # Name ids appearing in the input args
    enclosing: ast.AST             # the wrapper function def (or module)

    @property
    def line(self):
        return self.call.lineno


def _resolve_local_fn(name, scope_node):
    """A local `def name(...)` or `name = lambda ...` in `scope_node`
    (not descending into nested defs)."""
    body = scope_node.body if isinstance(scope_node.body, list) else []
    for stmt in body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name == name:
            return stmt
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name \
                and isinstance(stmt.value, ast.Lambda):
            return stmt.value
        # one level of if/else nesting covers the corpus idiom
        # (`if training: ... def fn ...`)
        if isinstance(stmt, (ast.If, ast.For, ast.While, ast.With)):
            found = _resolve_local_fn(name, stmt)
            if found is not None:
                return found
    return None


def dispatch_sites(module):
    """Every funnel call in `module`, with the fn resolved and the
    dispatch-input names collected. Skips ops/dispatch.py and
    ops/_helpers.py themselves (they DEFINE the funnel)."""
    if module.rel.endswith(("ops/dispatch.py", "ops/_helpers.py")):
        return []
    parents = module.parents()
    sites = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) not in _DISPATCH_WRAPPERS:
            continue
        if len(node.args) < 2:
            continue
        op_name = ""
        if isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            op_name = node.args[0].value
        fn_expr = node.args[1]
        enclosing = enclosing_function(node, parents) or module.tree
        fn_node = None
        if isinstance(fn_expr, ast.Lambda):
            fn_node = fn_expr
        elif isinstance(fn_expr, ast.Name):
            scope = enclosing
            while fn_node is None:
                if hasattr(scope, "body"):
                    fn_node = _resolve_local_fn(fn_expr.id, scope)
                if fn_node is not None or scope is module.tree:
                    break
                scope = enclosing_function(scope, parents) or module.tree
        input_names = set()
        for arg in node.args[2:]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name):
                    input_names.add(sub.id)
        sites.append(DispatchSite(call=node, op_name=op_name,
                                  fn_expr=fn_expr, fn_node=fn_node,
                                  input_names=input_names,
                                  enclosing=enclosing))
    return sites
