"""Baseline suppressions for the fusion linter.

A suppression acknowledges a KNOWN, commented finding without hiding the
rule: the linter still sees the violation, the baseline just stops it
from failing CI. Keys are (rule, file, symbol) — line numbers drift with
every edit above them, so a suppression pinned to the enclosing function
qualname survives refactors that do not move the offending code between
functions.

Baseline hygiene is two-sided and both sides are tested:

  * `match` — a finding covered by an entry is suppressed;
  * `stale` — an entry matching NO current finding is expired (the bug
    it acknowledged was fixed); `fusion_lint --baseline` prints expired
    entries so the file never accumulates dead weight, and
    `--write-baseline` regenerates it from the live findings.

File format: JSON with a mandatory human `note` per entry — a
suppression without a recorded justification is how "temporary" becomes
"forever".
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

__all__ = ["Baseline", "DEFAULT_BASELINE"]

# the checked-in repo baseline (tools/fusion_lint.py --baseline default)
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tools", "fusion_lint_baseline.json")

_VERSION = 1


@dataclass
class Baseline:
    entries: list = field(default_factory=list)   # [dict]

    # -- persistence --------------------------------------------------------
    @classmethod
    def load(cls, path):
        if not os.path.exists(path):
            return cls()
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != _VERSION:
            raise ValueError(
                f"baseline {path}: unsupported version "
                f"{data.get('version')!r} (expected {_VERSION})")
        return cls(entries=list(data.get("suppressions") or []))

    def save(self, path):
        data = {"version": _VERSION, "suppressions": self.entries}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")
        os.replace(tmp, path)

    # -- editing ------------------------------------------------------------
    def add(self, finding, note=""):
        """Suppress one finding (idempotent)."""
        entry = {"rule": finding.rule, "file": finding.file,
                 "symbol": finding.symbol,
                 "reason_code": finding.reason_code,
                 "note": note or "suppressed without justification "
                                 "(fill me in)"}
        key = (entry["rule"], entry["file"], entry["symbol"])
        for e in self.entries:
            if (e.get("rule"), e.get("file"), e.get("symbol")) == key:
                return e
        self.entries.append(entry)
        return entry

    # -- matching -----------------------------------------------------------
    def _covers(self, entry, finding):
        if entry.get("rule") != finding.rule \
                or entry.get("file") != finding.file:
            return False
        sym = entry.get("symbol", "")
        return sym == "*" or sym == finding.symbol

    def match(self, finding):
        """The entry suppressing `finding`, or None."""
        for e in self.entries:
            if self._covers(e, finding):
                return e
        return None

    def split(self, findings):
        """(unsuppressed, suppressed) partition of `findings`."""
        live, muted = [], []
        for f in findings:
            (muted if self.match(f) else live).append(f)
        return live, muted

    def stale(self, findings):
        """Entries that cover NO current finding — expired suppressions
        whose underlying violation was fixed; prune them."""
        out = []
        for e in self.entries:
            if not any(self._covers(e, f) for f in findings):
                out.append(e)
        return out

    def expire(self, findings):
        """Drop stale entries in place; returns the removed entries."""
        dead = self.stale(findings)
        if dead:
            self.entries = [e for e in self.entries if e not in dead]
        return dead
