"""Promotion-safety static analyzer: the fusion linter.

Every promotion-poisoning bug class this repo has shipped so far —
unkeyable closure captures (PRs 3-4 threaded masks/labels/ids as dispatch
inputs one at a time), stateful RNG outside the fold_in stream (PR 14),
unkeyed collectives (PR 10), tracer leaks into the guardian queue, and
host-sync peeks that split cycles — was discovered at RUNTIME by the
flight recorder, usually after a whole PR of debugging. The reference
stack gets the same guarantee from its static-graph compiler passes (PHI
kernel registration + pass infrastructure); this package is the
TPU-native, eager-first equivalent: an AST pass over the op/nn/serving
layers that proves the promotion contracts hold at CI time, speaking the
SAME `REASON_CODES` vocabulary the fusion doctor already speaks — a
static finding and a runtime flight-recorder attribution are one
taxonomy.

Layout:

  analyzer.py   shared AST machinery: project loading, scope/closure
                resolution (free-variable computation + a light taint
                pass classifying names as Tensor/array/scalar), dispatch
                call-site discovery, the Finding record
  rules/        one module per rule (R1-R6), registered via @rule
  baseline.py   checked-in suppression file (add / match / expire)
  report.py     findings as {rule, file:line, reason_code, hint} dicts,
                JSON schema + text rendering, contract validation
                against the live REASON_CODES / REASON_HINTS

CLI: ``python tools/fusion_lint.py [--json] [--baseline] [--fix-hints]``
— non-zero exit on unsuppressed findings; wired into tier-1 via
tests/test_fusion_lint.py. `fusion_doctor --lint` cross-references
runtime split reasons with static findings ("this rng_rekey split was
statically predicted at ops/random_ops.py:NN").
"""
from .analyzer import Finding, Project, load_project, run_rules, RULE_DOCS
from .baseline import Baseline
from .report import (findings_to_dicts, render_text, render_json,
                     validate_findings)

__all__ = ["Finding", "Project", "load_project", "run_rules", "RULE_DOCS",
           "Baseline", "findings_to_dicts", "render_text", "render_json",
           "validate_findings", "analyze"]


def analyze(root=None, paths=None, rules=None):
    """One-call convenience: load the project and run the rule set.
    Returns a sorted list of Finding records."""
    project = load_project(root=root, paths=paths)
    return run_rules(project, rules=rules)
