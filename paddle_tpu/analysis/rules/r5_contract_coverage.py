"""R5 contract-coverage: the public observability contracts must stay
closed under extension.

The repo's taxonomy lives in four frozen surfaces: `REASON_CODES` /
`CATEGORIES` (profiler/events.py), `REASON_HINTS` (profiler/explain.py),
`METRIC_NAMES` / `METRIC_MERGE` (profiler/metrics.py), and the
`define_flag` registry (framework/flags.py). Every PR so far extended
one of them; the failure mode is drift — a reason code without a doctor
hint, a metric without a fleet merge policy, an emitted event category
off the contract, a `FLAGS_*` read that was never registered (a typo'd
flag silently reads None forever). Each drift is invisible at runtime
until a doctor report renders a bare code or a fleet merge guesses a
policy.

All checks are purely static (AST literal extraction), so the rule runs
on fixture trees exactly like the real one:

  * every REASON_CODES entry has a REASON_HINTS entry (and vice versa);
  * every METRIC_NAMES entry has a METRIC_MERGE policy (and vice versa);
  * every literal category passed to `*.emit(...)` is in CATEGORIES;
  * every literal reason passed to `*.emit(...)` is in REASON_CODES;
  * every `FLAGS_*` string literal used outside the registry is defined
    by a `define_flag` call;
  * every literal metric name registered via `.counter/.gauge/
    .histogram(...)` inside the package is in METRIC_NAMES.
"""
from __future__ import annotations

import ast
import re

from ..analyzer import Finding, call_name, qualname_of
from . import rule

_FLAG_RE = re.compile(r"^FLAGS_[A-Za-z0-9_]+$")
_METRIC_REGISTERERS = {"counter", "gauge", "histogram"}


@rule
class ContractCoverage:
    id = "R5"
    title = "observability contract drift"
    reason_code = "contract_drift"
    hint = ("keep the taxonomy closed: add the missing REASON_HINTS / "
            "METRIC_MERGE / CATEGORIES / define_flag entry next to the "
            "code that introduced the new name, and update the "
            "contract-freeze tests (tests/test_fusion_events.py, "
            "tests/test_metrics.py) deliberately")

    def run(self, project):
        sets = {}        # name -> (set, module, line)
        maps = {}        # name -> (keys, module, line)
        flags = {}       # flag -> line  (define_flag registry)
        flags_file = None
        for module in project.modules:
            for stmt in ast.walk(module.tree):
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    name = stmt.targets[0].id
                    if name in ("REASON_CODES", "CATEGORIES",
                                "METRIC_NAMES"):
                        vals = _frozenset_strings(stmt.value)
                        if vals is not None:
                            sets[name] = (vals, module, stmt.lineno)
                    elif name in ("REASON_HINTS", "METRIC_MERGE"):
                        keys = _dict_string_keys(stmt.value)
                        if keys is not None:
                            maps[name] = (keys, module, stmt.lineno)
                elif isinstance(stmt, ast.Call) \
                        and call_name(stmt) == "define_flag" \
                        and stmt.args \
                        and isinstance(stmt.args[0], ast.Constant) \
                        and isinstance(stmt.args[0].value, str):
                    flags[stmt.args[0].value] = stmt.lineno
                    flags_file = module.rel

        # -- set/map pairings -----------------------------------------------
        yield from self._pair(sets, maps, "REASON_CODES", "REASON_HINTS",
                              "doctor hint (REASON_HINTS)")
        yield from self._pair(sets, maps, "METRIC_NAMES", "METRIC_MERGE",
                              "fleet merge policy (METRIC_MERGE)")

        codes = sets.get("REASON_CODES", (frozenset(), None, 0))[0]
        cats = sets.get("CATEGORIES", (frozenset(), None, 0))[0]
        metric_names = sets.get("METRIC_NAMES", (frozenset(), None, 0))[0]

        # -- per-module literal checks --------------------------------------
        for module in project.modules:
            if module.rel == flags_file:
                continue
            parents = None
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name == "emit" and node.args:
                    parents = parents or module.parents()
                    yield from self._check_emit(node, module, parents,
                                                cats, codes)
                elif name in _METRIC_REGISTERERS and metric_names \
                        and not module.rel.startswith("tools/") \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and isinstance(node.func, ast.Attribute):
                    mn = node.args[0].value
                    if mn not in metric_names:
                        parents = parents or module.parents()
                        yield Finding(
                            rule=self.id, file=module.rel,
                            line=node.lineno,
                            reason_code=self.reason_code,
                            message=(f"metric `{mn}` registered off the "
                                     "METRIC_NAMES contract"),
                            symbol=qualname_of(node, parents))
            if flags:
                yield from self._check_flags(module, flags)

    # -- helpers ------------------------------------------------------------
    def _pair(self, sets, maps, set_name, map_name, what):
        if set_name not in sets or map_name not in maps:
            return
        vals, mod, line = sets[set_name]
        keys, mmod, mline = maps[map_name]
        for missing in sorted(vals - keys):
            yield Finding(
                rule=self.id, file=mod.rel, line=line,
                reason_code=self.reason_code,
                message=f"{set_name} entry `{missing}` has no {what}",
                symbol=set_name)
        for stale in sorted(keys - vals):
            yield Finding(
                rule=self.id, file=mmod.rel, line=mline,
                reason_code=self.reason_code,
                message=(f"{map_name} entry `{stale}` is not in "
                         f"{set_name} (stale or typo)"),
                symbol=map_name)

    def _check_emit(self, node, module, parents, cats, codes):
        cat = node.args[0]
        if cats and isinstance(cat, ast.Constant) \
                and isinstance(cat.value, str) and "." in cat.value \
                and cat.value not in cats:
            yield Finding(
                rule=self.id, file=module.rel, line=node.lineno,
                reason_code=self.reason_code,
                message=(f"event category `{cat.value}` emitted off the "
                         "CATEGORIES contract"),
                symbol=qualname_of(node, parents))
        reason = None
        if len(node.args) >= 4:
            reason = node.args[3]
        for kw in node.keywords or ():
            if kw.arg == "reason":
                reason = kw.value
        if codes and isinstance(reason, ast.Constant) \
                and isinstance(reason.value, str) \
                and reason.value not in codes:
            yield Finding(
                rule=self.id, file=module.rel, line=node.lineno,
                reason_code=self.reason_code,
                message=(f"reason `{reason.value}` emitted off the "
                         "REASON_CODES contract"),
                symbol=qualname_of(node, parents))

    def _check_flags(self, module, flags):
        parents = None
        docstrings = _docstring_nodes(module.tree)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _FLAG_RE.match(node.value) \
                    and id(node) not in docstrings \
                    and node.value not in flags:
                parents = parents or module.parents()
                yield Finding(
                    rule=self.id, file=module.rel, line=node.lineno,
                    reason_code=self.reason_code,
                    message=(f"`{node.value}` read/written but never "
                             "registered via define_flag"),
                    symbol=qualname_of(node, parents))


def _frozenset_strings(node):
    """{"a", "b"} out of `frozenset({...})` / a bare set literal."""
    if isinstance(node, ast.Call) and call_name(node) == "frozenset" \
            and node.args:
        node = node.args[0]
    if isinstance(node, ast.Set):
        vals = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                vals.add(el.value)
            else:
                return None
        return frozenset(vals)
    return None


def _dict_string_keys(node):
    if isinstance(node, ast.Dict):
        keys = set()
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
            else:
                return None
        return frozenset(keys)
    return None


def _docstring_nodes(tree):
    """id()s of Constant nodes in docstring position (module / class /
    def first statement) — prose mentioning FLAGS_* is not a read."""
    out = set()
    for node in ast.walk(tree):
        body = getattr(node, "body", None)
        if isinstance(body, list) and body \
                and isinstance(body[0], ast.Expr) \
                and isinstance(body[0].value, ast.Constant) \
                and isinstance(body[0].value.value, str):
            out.add(id(body[0].value))
    return out
