"""R7 perf-contract: new compiled-path surface area must stay visible to
the performance accounting plane.

The regression sentinel (profiler/sentinel.py) and its checked-in bands
(tools/perf_baselines.json) are only as good as two inputs:

  * the analytic FLOPs estimator (`goodput.estimate_cycle_flops`) — an
    op that does matmul-class work but falls through to the O(numel)
    default silently deflates MFU/goodput and the drift verdicts built
    on them;
  * the AOT env fingerprint (`aot_cache.env_fingerprint`) — a flag that
    steers what a compiled program LOOKS like but is absent from the
    fingerprint lets one process deserialize another's artifacts, which
    surfaces as unexplained perf drift rather than a crash.

Two purely static checks, mirroring that split:

  * every `@register_op` function whose body touches heavy contraction
    math (einsum / matmul / tensordot / `@` / ...) must dispatch under a
    name the estimator's family heuristic recognizes ("matmul" in name,
    mm/bmm/addmm/linear, conv/attention/softmax/embedding) OR have an
    explicit `declare_op_flops("name", ...)` declaration somewhere in
    the tree;
  * every `FLAGS_*` string literal used in a module that registers ops
    must appear in the fingerprint's flag tuple (inside
    `env_fingerprint`) OR in the `FUSION_NEUTRAL_FLAGS` frozenset
    (ops/aot_cache.py) that records the deliberate judgment "this knob
    cannot change a lowered program". The flag check is skipped on
    trees that carry neither surface (isolated fixture trees).

Like every rule, findings carry a REASON_CODES entry (`perf_contract`)
shared with the runtime taxonomy, and deliberate exceptions live in
tools/fusion_lint_baseline.json (e.g. einsum, whose cost depends on the
equation string, not the operand shapes alone).
"""
from __future__ import annotations

import ast
import re

from ..analyzer import Finding, call_name, decorator_op_name, qualname_of
from . import rule

_FLAG_RE = re.compile(r"^FLAGS_[A-Za-z0-9_]+$")

# attribute names that mean "this op does contraction-class work" —
# whether called (`jnp.einsum(...)`) or passed as the kernel callable
# (`binary("inner", jnp.inner, ...)`)
_HEAVY_ATTRS = frozenset({
    "einsum", "matmul", "dot", "dot_general", "tensordot", "inner",
    "outer", "vdot", "multi_dot", "matrix_power", "kron",
})

# the wrappers whose first string argument is the dispatch name the
# goodput estimator will see as the cache key's key[0]
_DISPATCHERS = frozenset({"unary", "binary", "nary", "call_op"})

# name families `goodput._flops_of_op` recognizes analytically — keep in
# sync with that function (R7's own fixture freezes this list)
_COVERED_EXACT = frozenset({"linear", "mm", "bmm", "addmm"})
_COVERED_SUBSTR = ("matmul", "conv", "attention", "softmax", "embedding")


def _family_covered(name):
    return name in _COVERED_EXACT or any(s in name for s in _COVERED_SUBSTR)


@rule
class PerfContract:
    id = "R7"
    title = "perf-contract drift (FLOPs coverage / flag fingerprint)"
    reason_code = "perf_contract"
    hint = ("keep new compiled-path surface visible to the perf plane: "
            "give heavy ops an estimator the goodput accountant can use "
            "(dispatch under a matmul-family name or add a "
            "`declare_op_flops(\"<name>\", fn)` in profiler/goodput.py) "
            "and classify new compiled-path flags (add to the "
            "`env_fingerprint` flags tuple if they change the lowered "
            "program, to `FUSION_NEUTRAL_FLAGS` in ops/aot_cache.py with "
            "a rationale if they cannot)")

    def run(self, project):
        declared, fp_flags, neutral = self._contract_surfaces(project)
        for module in project.modules:
            parents = None
            opfuncs = [n for n in ast.walk(module.tree)
                       if isinstance(n, ast.FunctionDef)
                       and decorator_op_name(n) is not None]
            for fn in opfuncs:
                finding = self._check_flops(fn, module, declared)
                if finding is not None:
                    parents = parents or module.parents()
                    yield Finding(
                        rule=self.id, file=module.rel, line=fn.lineno,
                        reason_code=self.reason_code,
                        message=finding,
                        symbol=qualname_of(fn, parents))
            # flag classification only applies to op-registering modules
            # (the compiled-op path), and only on trees that carry the
            # fingerprint/neutral surfaces at all
            if opfuncs and (fp_flags or neutral):
                known = fp_flags | neutral
                docstrings = _docstring_nodes(module.tree)
                for node in ast.walk(module.tree):
                    if isinstance(node, ast.Constant) \
                            and isinstance(node.value, str) \
                            and _FLAG_RE.match(node.value) \
                            and id(node) not in docstrings \
                            and node.value not in known:
                        parents = parents or module.parents()
                        yield Finding(
                            rule=self.id, file=module.rel,
                            line=node.lineno,
                            reason_code=self.reason_code,
                            message=(f"compiled-path flag `{node.value}` "
                                     "is neither in the env_fingerprint "
                                     "flags tuple nor declared in "
                                     "FUSION_NEUTRAL_FLAGS"),
                            symbol=qualname_of(node, parents))

    # -- contract surface collection ----------------------------------------
    def _contract_surfaces(self, project):
        """(declared FLOPs names, fingerprinted flags, neutral flags),
        each collected from literals anywhere in the tree."""
        declared, fp_flags, neutral = set(), set(), set()
        for module in project.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call) \
                        and call_name(node) == "declare_op_flops" \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    declared.add(node.args[0].value)
                elif isinstance(node, ast.FunctionDef) \
                        and node.name == "env_fingerprint":
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str) \
                                and _FLAG_RE.match(sub.value):
                            fp_flags.add(sub.value)
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == "FUSION_NEUTRAL_FLAGS":
                    vals = _frozenset_strings(node.value)
                    if vals is not None:
                        neutral |= vals
        return declared, frozenset(fp_flags), frozenset(neutral)

    # -- FLOPs coverability --------------------------------------------------
    def _check_flops(self, fn, module, declared):
        heavy = set()
        dispatch = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _HEAVY_ATTRS:
                heavy.add(node.attr)
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.MatMult):
                heavy.add("@")
            elif isinstance(node, ast.Call) \
                    and call_name(node) in _DISPATCHERS \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                dispatch.add(node.args[0].value)
        if not heavy:
            return None
        names = dispatch or {decorator_op_name(fn)}
        names = set(names) | {decorator_op_name(fn)}
        if any(_family_covered(n) or n in declared for n in names):
            return None
        pretty = ", ".join(sorted(heavy))
        return (f"op does heavy contraction work ({pretty}) but none of "
                f"its dispatch names ({', '.join(sorted(names))}) is "
                "coverable by estimate_cycle_flops — declare its cost "
                "via declare_op_flops or dispatch under a matmul-family "
                "name")


def _frozenset_strings(node):
    """{"a", "b"} out of `frozenset({...})` / a bare set literal."""
    if isinstance(node, ast.Call) and call_name(node) == "frozenset" \
            and node.args:
        node = node.args[0]
    if isinstance(node, ast.Set):
        vals = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                vals.add(el.value)
            else:
                return None
        return frozenset(vals)
    return None


def _docstring_nodes(tree):
    """id()s of Constant nodes in docstring position."""
    out = set()
    for node in ast.walk(tree):
        body = getattr(node, "body", None)
        if isinstance(body, list) and body \
                and isinstance(body[0], ast.Expr) \
                and isinstance(body[0].value, ast.Constant) \
                and isinstance(body[0].value.value, str):
            out.add(id(body[0].value))
    return out
