"""Rule registry: one module per rule, registered via the @rule
decorator at import. Each rule is a singleton with:

  id           "R1".."R6"
  title        short human name
  reason_code  the REASON_CODES entry its findings carry (static findings
               and runtime flight-recorder attributions are ONE taxonomy)
  hint         the actionable fix, rendered by `fusion_lint --fix-hints`
  run(project) -> iterable of Finding
"""
from ..analyzer import RULE_DOCS

RULES = []


def rule(cls):
    inst = cls()
    RULES.append(inst)
    RULES.sort(key=lambda r: r.id)
    RULE_DOCS[inst.id] = {"title": inst.title,
                          "reason_code": inst.reason_code,
                          "hint": inst.hint}
    return cls


from . import r1_unkeyable_closure   # noqa: E402,F401
from . import r2_stateful_rng        # noqa: E402,F401
from . import r3_host_sync           # noqa: E402,F401
from . import r4_unkeyed_collective  # noqa: E402,F401
from . import r5_contract_coverage   # noqa: E402,F401
from . import r6_lock_discipline     # noqa: E402,F401
from . import r7_perf_contract       # noqa: E402,F401

__all__ = ["RULES", "rule"]
