"""R4 unkeyed-collective: a process-group collective call that is not
stamped with `dispatch.mark_collective` before entering the funnel.

A collective's fn closes over a compiled process-group callable —
unkeyable by the closure scan — but its identity is fully determined by
(kind, reduce-op, mesh key). PR 10 made `mark_collective` stamp that
identity onto the fn so `_fn_token` keys it before any closure walk; a
pg call that reaches dispatch WITHOUT the stamp (or never reaches
dispatch at all) is the `collective_unkeyed` bug class: it bypasses the
cache and poisons every training cycle containing it.

Detection, matching the distributed/collective.py idiom: a data-plane
pg call (`pg.all_reduce(...)`, `pg.gather_all(...)`, ...) is clean only
when it sits inside a fn/lambda that flows through a MARKING funnel — a
local function that itself calls `mark_collective` (e.g.
`_dispatch_collective`) — or when `mark_collective` is applied in the
same scope. Anything else is flagged; deliberate host-mediated paths
(object gathers) are suppressed in the checked-in baseline, not hidden
from the rule.

PR 16 widened the surface to the SPMD axis-name collectives: a
`jax.lax` collective (`ppermute`, `all_to_all`, `psum`, ...) written
inside an fn that is eagerly dispatched (`call_op`/`call_op_multi`) is
the same bug class — the closure scan cannot key the axis binding, so
the site poisons every cycle containing it unless stamped. The dispatch
edge is the trigger: `lax` collectives inside shard_map/jit-only bodies
(distributed/collective.py's compiled process-group programs, the
pipeline ppermute scan) never reach the funnel and are exempt. Scope
covers every collective-bearing tree: `distributed/` (including
`fleet/meta_parallel/`) and `incubate/distributed/` (MoE).
"""
from __future__ import annotations

import ast

from ..analyzer import (Finding, call_name, enclosing_function,
                        qualname_of)
from . import rule

# the data-plane collective surface (host-mediated p2p stays
# control-plane by design and is exempt)
_PG_KINDS = {"all_reduce", "all_gather", "gather_all", "broadcast",
             "reduce_scatter", "alltoall", "alltoall_single", "scatter",
             "reduce"}

# SPMD axis-name collectives: flagged only when the containing fn is
# eagerly dispatched — inside compiled shard_map/jit bodies they are the
# intended lowering and never touch the dispatch cache
_LAX_KINDS = {"psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
              "ppermute", "pshuffle", "psum_scatter"}
_DISPATCHERS = {"call_op", "call_op_multi"}

# every tree that carries collectives: distributed/ (which includes
# fleet/meta_parallel/) plus incubate/distributed/ (MoE)
_SCOPES = ("/distributed/", "/incubate/", "/meta_parallel/")


@rule
class UnkeyedCollective:
    id = "R4"
    title = "collective without mark_collective"
    reason_code = "collective_unkeyed"
    hint = ("route the pg call through a funnel that stamps "
            "dispatch.mark_collective((kind, op, mesh_key)) on the fn "
            "(the _dispatch_collective pattern of PR 10) so the "
            "collective keys by (kind, reduce-op, mesh) — or, for a "
            "group with no mesh-backed pg, dispatch the explicit "
            "collective_unkeyed marker so the poison is attributed "
            "instead of silent")

    def run(self, project):
        for module in project.modules:
            rel = "/" + module.rel
            if not any(scope in rel for scope in _SCOPES):
                continue
            parents = module.parents()
            marking = _marking_functions(module.tree)
            dispatched = _dispatched_fn_names(module.tree)
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if not isinstance(node.func, ast.Attribute):
                    continue
                if name in _PG_KINDS and _pg_receiver(node.func.value):
                    if _flows_through_marker(node, parents, marking):
                        continue
                    yield Finding(
                        rule=self.id, file=module.rel, line=node.lineno,
                        reason_code=self.reason_code,
                        message=(f"pg collective `{name}` is not "
                                 "stamped with dispatch.mark_collective — "
                                 "unkeyable in the funnel"),
                        symbol=qualname_of(node, parents))
                elif name in _LAX_KINDS \
                        and _lax_receiver(node.func.value) \
                        and _reaches_dispatch(node, parents, dispatched) \
                        and not _flows_through_marker(node, parents,
                                                      marking):
                    yield Finding(
                        rule=self.id, file=module.rel, line=node.lineno,
                        reason_code=self.reason_code,
                        message=(f"lax collective `{name}` inside an "
                                 "eagerly dispatched fn without a "
                                 "dispatch.mark_collective stamp — the "
                                 "closure scan cannot key the axis "
                                 "binding"),
                        symbol=qualname_of(node, parents))


def _pg_receiver(node):
    """True when the call receiver is a process group: a name containing
    "pg", or an attribute chain ending in .pg (group.pg, self.pg)."""
    if isinstance(node, ast.Name):
        return node.id == "pg" or node.id.endswith("_pg")
    if isinstance(node, ast.Attribute):
        return node.attr == "pg"
    return False


def _lax_receiver(node):
    """True when the call receiver is the lax namespace: `lax.psum` or
    `jax.lax.psum`."""
    if isinstance(node, ast.Name):
        return node.id == "lax"
    if isinstance(node, ast.Attribute):
        return node.attr == "lax"
    return False


def _dispatched_fn_names(tree):
    """Names passed (by name) as arguments to call_op/call_op_multi —
    the fns that enter the eager funnel."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and call_name(node) in _DISPATCHERS:
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, ast.Name):
                    out.add(a.id)
    return out


def _reaches_dispatch(node, parents, dispatched):
    """The lax call sits inside a def/lambda that enters the funnel:
    a lambda inlined into a call_op/call_op_multi call, or a named def
    that is passed to one somewhere in the module."""
    fn = enclosing_function(node, parents)
    while fn is not None:
        parent = parents.get(fn)
        if isinstance(parent, ast.Call) \
                and call_name(parent) in _DISPATCHERS:
            return True
        if isinstance(fn, ast.FunctionDef) and fn.name in dispatched:
            return True
        fn = enclosing_function(fn, parents)
    return False


def _marking_functions(tree):
    """Names of module/local functions that call mark_collective — the
    marking funnels a pg-fn may flow through."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and call_name(sub) == "mark_collective":
                    out.add(node.name)
                    break
    return out


def _flows_through_marker(node, parents, marking):
    """The pg call is inside a def/lambda that is (a) an argument to a
    marking-funnel call, (b) itself a marking function, or (c) passed to
    mark_collective in the enclosing scope."""
    fn = enclosing_function(node, parents)
    while fn is not None:
        if isinstance(fn, ast.FunctionDef) and fn.name in marking:
            return True
        parent = parents.get(fn)
        if isinstance(parent, ast.Call):
            callee = call_name(parent)
            if callee in marking or callee == "mark_collective":
                return True
        if isinstance(fn, ast.FunctionDef):
            # `def fn(...)` then `mark_collective(fn, key)` later in the
            # same scope
            outer = enclosing_function(fn, parents)
            scope_body = getattr(outer, "body", None) or []
            for stmt in scope_body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) \
                            and call_name(sub) == "mark_collective" \
                            and sub.args \
                            and isinstance(sub.args[0], ast.Name) \
                            and sub.args[0].id == fn.name:
                        return True
        fn = enclosing_function(fn, parents)
    return False
