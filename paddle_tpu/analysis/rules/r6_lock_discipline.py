"""R6 lock-discipline: in the serving engine and the telemetry plane, no
blocking I/O or callback invocation while holding a registry/scheduler
lock, and a consistent lock acquisition order.

These are the race classes the chaos harness can only SAMPLE: a
`time.sleep`/socket read under the metrics registry lock turns a 100 Hz
scrape into a convoyed decode step; an `on_token` user callback invoked
under a scheduler lock can re-enter `cancel()` and deadlock; two
functions taking the same pair of locks in opposite orders deadlock once
per blue moon under load. The scan is scoped to the modules where a held
lock sits on the serving/telemetry hot path: `paddle_tpu/serving/`,
`profiler/metrics.py`, `profiler/goodput.py`,
`profiler/telemetry_server.py`, and the elastic-fabric control plane
`distributed/fabric.py` — a heartbeat RPC or event emission under the
membership lock stalls every join/heartbeat/reap on the fleet (fixtures
ride along via `serving/`- and `distributed/`-named directories).

Lock identity is the attribute/name spelled at the `with` site (any
name containing "lock"); acquisition order is tracked per module as
(outer, inner) edges — an edge pair in both directions is an inversion.
"""
from __future__ import annotations

import ast

from ..analyzer import (Finding, call_name, dotted_name, qualname_of)
from . import rule

# calls that block (or can block unboundedly) — forbidden under a lock
_BLOCKING_NAMES = {"sleep", "open", "print", "urlopen", "input",
                   "block_until_ready"}
_BLOCKING_DOTTED_HEADS = {"subprocess", "os.system", "os.popen",
                          "shutil", "urllib"}
# invoking user/observer code under a lock: re-entrancy + unbounded time
_CALLBACK_CONTAINERS = ("callback", "collector", "hook", "listener",
                        "waiter", "observer")


def _in_scope(rel):
    return ("/serving/" in "/" + rel or rel.startswith("serving/")
            or rel.endswith(("profiler/metrics.py", "profiler/goodput.py",
                             "profiler/telemetry_server.py",
                             "distributed/fabric.py")))


def _lock_token(expr):
    """"_lock" out of `self._lock` / `_cache_lock` / `reg._ring_lock` —
    None when the with-item is not a lock."""
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Call):
        return None     # lock() factories / helpers: not a held lock name
    if name and "lock" in name.lower():
        return name
    return None


@rule
class LockDiscipline:
    id = "R6"
    title = "blocking work / inversion under lock"
    reason_code = "lock_discipline"
    hint = ("move the blocking call / callback invocation outside the "
            "`with lock:` block (snapshot under the lock, act after "
            "release — the registry collector pattern), and keep one "
            "global lock acquisition order; a scrape or user callback "
            "must never run while a registry/scheduler lock is held")

    def run(self, project):
        for module in project.modules:
            if not _in_scope(module.rel):
                continue
            parents = module.parents()
            edges = {}            # (outer, inner) -> (line, symbol)
            findings = []
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.With):
                    continue
                tokens = [t for t in
                          (_lock_token(i.context_expr)
                           for i in node.items) if t]
                if not tokens:
                    continue
                held = tokens[0]
                findings.extend(
                    self._scan_body(node, module, parents, held, edges))
            # inversion: both (a, b) and (b, a) acquired somewhere in the
            # module — report at the LATER edge (stable, deterministic)
            for (a, b), (line, sym) in sorted(edges.items(),
                                              key=lambda kv: kv[1][0]):
                if (b, a) in edges and edges[(b, a)][0] < line:
                    findings.append(Finding(
                        rule=self.id, file=module.rel, line=line,
                        reason_code=self.reason_code,
                        message=(f"lock order inversion: `{a}` -> `{b}` "
                                 f"here, but `{b}` -> `{a}` at line "
                                 f"{edges[(b, a)][0]}"),
                        symbol=sym))
            yield from findings

    def _scan_body(self, with_node, module, parents, held, edges):
        callback_vars = set()
        for stmt in with_node.body:
            for node in _walk_pruned(stmt):
                if isinstance(node, ast.With):
                    for item in node.items:
                        inner = _lock_token(item.context_expr)
                        if inner and inner != held:
                            edges.setdefault(
                                (held, inner),
                                (node.lineno,
                                 qualname_of(node, parents)))
                if isinstance(node, ast.For):
                    src = dotted_name(node.iter) or ""
                    if any(c in src.lower()
                           for c in _CALLBACK_CONTAINERS) \
                            and isinstance(node.target, ast.Name):
                        callback_vars.add(node.target.id)
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node) or ""
                dn = dotted_name(node.func) or ""
                head = dn.split(".")[0]
                if name in _BLOCKING_NAMES \
                        or head in _BLOCKING_DOTTED_HEADS \
                        or dn.startswith(("subprocess.", "urllib.")):
                    yield Finding(
                        rule=self.id, file=module.rel, line=node.lineno,
                        reason_code=self.reason_code,
                        message=(f"blocking call `{name or dn}()` while "
                                 f"holding `{held}`"),
                        symbol=qualname_of(node, parents))
                elif _is_callback_invocation(node, callback_vars):
                    yield Finding(
                        rule=self.id, file=module.rel, line=node.lineno,
                        reason_code=self.reason_code,
                        message=(f"callback `{name}()` invoked while "
                                 f"holding `{held}` (re-entrancy / "
                                 "unbounded hold time)"),
                        symbol=qualname_of(node, parents))


def _is_callback_invocation(node, callback_vars):
    name = call_name(node) or ""
    if isinstance(node.func, ast.Name) and node.func.id in callback_vars:
        return True
    low = name.lower()
    if low.startswith("on_"):
        return True
    return any(c in low for c in _CALLBACK_CONTAINERS) \
        and not low.startswith(("_run",))


def _walk_pruned(stmt):
    """Descend without entering nested def/lambda bodies (deferred
    execution does not run under the lock)."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)
