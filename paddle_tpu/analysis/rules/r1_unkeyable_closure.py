"""R1 unkeyable-closure: an op fn passed into the dispatch funnel
captures a Tensor / raw array (or reads module-level mutable state) that
never enters the dispatch-input list.

This is the PR 3/4 bug class verbatim: embedding ids, cross_entropy
labels, and attention masks were baked into op closures one at a time,
each silently poisoning every training cycle as `unkeyable_closure`
until the flight recorder caught it at runtime. Statically, the
signature is exact: diff the fn's free variables against the wrapper's
dispatch args; any capture with Tensor/array taint that is not also a
dispatch input cannot be value-keyed by `_fn_token`
(ops/dispatch.py) and will bypass the executable cache on every call.

Scalars, shapes, dtypes and module-level functions key by value and are
fine to capture — the taint pass (analyzer.TaintPass) only classifies
the assignment forms the corpus actually uses, so an unknown name is
never flagged.
"""
from __future__ import annotations

import ast

from ..analyzer import (Finding, TaintPass, dispatch_sites, free_loads,
                        qualname_of)
from . import rule


@rule
class UnkeyableClosure:
    id = "R1"
    title = "unkeyable closure capture"
    reason_code = "unkeyable_closure"
    hint = ("thread the captured Tensor/array through the op's dispatch "
            "inputs (the embedding-ids / cross_entropy-labels / "
            "attention-mask fix of PRs 3-4): the value becomes part of "
            "the cache key's avals and the op keys on structure instead "
            "of bypassing on every call")

    def run(self, project):
        for module in project.modules:
            parents = module.parents()
            mutable_globals = _mutable_globals(module.tree)
            for site in dispatch_sites(module):
                if site.fn_node is None:
                    continue
                enclosing = site.enclosing
                if not hasattr(enclosing, "body") or \
                        not isinstance(enclosing.body, list):
                    continue
                taint = TaintPass(enclosing)
                captured = free_loads(site.fn_node)
                for name, line in sorted(captured.items()):
                    if name in site.input_names:
                        continue
                    t = taint.of(name)
                    if t in ("tensor", "array"):
                        yield Finding(
                            rule=self.id, file=module.rel, line=line,
                            reason_code=self.reason_code,
                            message=(f"op `{site.op_name or '?'}` fn "
                                     f"captures {t} `{name}` that is not "
                                     "a dispatch input"),
                            symbol=qualname_of(site.call, parents))
                    elif name in mutable_globals \
                            and name not in site.input_names:
                        yield Finding(
                            rule=self.id, file=module.rel, line=line,
                            reason_code=self.reason_code,
                            message=(f"op `{site.op_name or '?'}` fn "
                                     f"reads mutable module global "
                                     f"`{name}` (dict/list/set state "
                                     "cannot be value-keyed)"),
                            symbol=qualname_of(site.call, parents))


def _mutable_globals(tree):
    """Module-level names assigned a dict/list/set display — mutable
    state an op fn must not read (the `_globals_token` bypass class)."""
    out = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, (ast.Dict, ast.List, ast.Set)):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out
