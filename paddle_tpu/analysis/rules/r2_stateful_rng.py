"""R2 stateful-rng: a registered op body draws from the global generator
(`get_rng_key()` / `split_key()` / `default_generator.next_key()`)
instead of reserving a hoisted stream position via
`framework/random.rng_key_input()`.

A stateful draw bakes a FRESH key into the op's closure on every call:
the op re-keys per call (`rng_rekey`), bypasses the executable cache,
and poisons every fusion cycle containing it — the exact bug class PR 14
closed for dropout/bernoulli by making randomness a fold_in STREAM whose
position rides as a lazy dispatch input. This rule freezes that win: any
`@register_op` body that still calls into the stateful generator is
flagged at CI time instead of being rediscovered by the flight recorder.

Scope is the registered op corpus. Init-time consumers
(nn/initializer.py), the distribution library, and jit tracing scopes
(jit/train_step.py threads a traced key by design) draw statefully on
purpose and are not op bodies.
"""
from __future__ import annotations

import ast

from ..analyzer import (Finding, call_name, decorator_op_name, dotted_name,
                        qualname_of)
from . import rule

_STATEFUL_CALLS = {"get_rng_key", "split_key"}


@rule
class StatefulRng:
    id = "R2"
    title = "stateful RNG in op body"
    reason_code = "rng_rekey"
    hint = ("reserve a stream position with framework/random."
            "rng_key_input() and pass the lazy key tensor as a dispatch "
            "input (the op wraps it back with jax.random.wrap_key_data "
            "inside its fn, deriving the SAME fold_in(base, i) key bits "
            "as the stateful draw) — the dropout/bernoulli pattern of "
            "PR 14; the op then keys on structure and promotes")

    def run(self, project):
        for module in project.modules:
            parents = module.parents()
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.FunctionDef):
                    continue
                op = decorator_op_name(node)
                if op is None:
                    continue
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    name = call_name(sub)
                    dn = dotted_name(sub.func) or ""
                    if name in _STATEFUL_CALLS \
                            or dn.endswith("default_generator.next_key"):
                        yield Finding(
                            rule=self.id, file=module.rel,
                            line=sub.lineno,
                            reason_code=self.reason_code,
                            message=(f"op `{op}` draws stateful global "
                                     f"randomness via `{name or dn}()` — "
                                     "bypasses rng_key_input() stream "
                                     "hoisting"),
                            symbol=qualname_of(sub, parents))
