"""R3 host-sync-in-hot-path: a dispatch-funnel wrapper forces a device
value to the host (`.numpy()`, `.item()`, `float()/int()/bool()` on a
Tensor, `np.asarray(tensor)`) on its way to `call_op`.

Inside a fused replay, every live Tensor may be a pending placeholder; a
host-forcing read materializes it and SPLITS the chain/step
(`mid_chain_escape` / `mid_step_peek` at runtime). PR 4 fixed exactly
this in the attention wrapper — eligibility peeks now read aval-safe
`Tensor.shape` / `_fusion_aval` metadata instead of forcing `_value`.
This rule pins the pattern: any function that dispatches through the
funnel must not force tensor values first.

The receiver must have Tensor taint (`x = ensure_tensor(x)` and
friends); host syncs on plain scalars/ndarray helpers outside funnel
wrappers are not the hot path and stay unflagged.
"""
from __future__ import annotations

import ast

from ..analyzer import (Finding, TaintPass, call_name, dispatch_sites,
                        qualname_of)
from . import rule

_FORCING_METHODS = {"numpy", "item"}
_FORCING_BUILTINS = {"float", "int", "bool"}


@rule
class HostSyncInHotPath:
    id = "R3"
    title = "host sync in dispatch hot path"
    reason_code = "mid_step_peek"
    hint = ("read shape/dtype through aval-safe metadata (Tensor.shape, "
            "ops/_helpers.jnp_dtype, _fusion_aval) instead of forcing "
            "the value, or move the host read after dispatch — a forced "
            "`.numpy()`/`.item()`/float() materializes pending fused "
            "placeholders and splits the chain/step it sits in (the "
            "PR 4 attention-eligibility fix)")

    def run(self, project):
        for module in project.modules:
            parents = module.parents()
            funnel_fns = {}
            for site in dispatch_sites(module):
                if hasattr(site.enclosing, "body") and \
                        isinstance(site.enclosing.body, list):
                    funnel_fns[id(site.enclosing)] = site.enclosing
            for fn in funnel_fns.values():
                taint = TaintPass(fn)
                for f in self._scan(fn, module, taint, parents):
                    yield f

    def _scan(self, fn, module, taint, parents):
        for stmt in fn.body:
            yield from self._scan_stmt(stmt, module, taint, parents)

    def _scan_stmt(self, stmt, module, taint, parents):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return      # the inner op fn runs in-graph, not on the host
        for node in _walk_pruned(stmt):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            recv = None
            if name in _FORCING_METHODS and \
                    isinstance(node.func, ast.Attribute):
                recv = node.func.value
            elif name in _FORCING_BUILTINS and isinstance(
                    node.func, ast.Name) and len(node.args) == 1:
                recv = node.args[0]
            elif name == "asarray" and isinstance(
                    node.func, ast.Attribute) and node.args:
                base = node.func.value
                if isinstance(base, ast.Name) \
                        and base.id in ("np", "numpy"):
                    recv = node.args[0]
                else:
                    continue
            else:
                continue
            t = taint.taint_of(recv) if recv is not None else None
            if t == "tensor":
                yield Finding(
                    rule=self.id, file=module.rel, line=node.lineno,
                    reason_code=self.reason_code,
                    message=(f"`{name}()` forces a Tensor value inside "
                             "a dispatch-funnel wrapper — splits any "
                             "pending fused chain/step"),
                    symbol=qualname_of(node, parents))


def _walk_pruned(stmt):
    """ast.walk that does NOT descend into nested def/lambda bodies —
    those run in-graph at trace time, not on the host path."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)
