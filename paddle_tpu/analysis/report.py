"""Finding rendering + contract validation for the fusion linter.

The JSON shape is a small public contract of its own (the CI gate and
`fusion_doctor --lint` both consume it; tests/test_fusion_lint.py
freezes the schema):

  {
    "version": 1,
    "findings": [{"rule", "file", "line", "symbol", "reason_code",
                  "message", "hint"}],
    "suppressed": [...same shape...],
    "stale_suppressions": [baseline entries],
    "rules": {"R1": {"title", "reason_code", "hint"}, ...},
    "summary": {"findings": N, "suppressed": N, "by_rule": {...}}
  }

Every finding's reason_code is validated against the LIVE
REASON_CODES / REASON_HINTS contracts (profiler/events.py,
profiler/explain.py) — a static finding and a runtime flight-recorder
attribution must remain one taxonomy, so a rule emitting an off-contract
code is itself a hard error.
"""
from __future__ import annotations

import json

from .analyzer import RULE_DOCS

__all__ = ["findings_to_dicts", "render_text", "render_json",
           "validate_findings", "REPORT_VERSION"]

REPORT_VERSION = 1


def _rule_hint(rule_id):
    doc = RULE_DOCS.get(rule_id) or {}
    return doc.get("hint", "")


def findings_to_dicts(findings):
    return [{"rule": f.rule, "file": f.file, "line": f.line,
             "symbol": f.symbol, "reason_code": f.reason_code,
             "message": f.message, "hint": _rule_hint(f.rule)}
            for f in findings]


def validate_findings(findings):
    """Every finding must carry a valid REASON_CODES entry that also has
    a REASON_HINTS doctor hint. Returns the offending codes (empty =
    valid); the CLI treats a non-empty answer as an internal error."""
    from ..profiler.events import REASON_CODES
    from ..profiler.explain import REASON_HINTS
    bad = sorted({f.reason_code for f in findings
                  if f.reason_code not in REASON_CODES
                  or f.reason_code not in REASON_HINTS})
    return bad


def render_json(findings, suppressed=(), stale=(), indent=2):
    from . import rules  # ensure RULE_DOCS is populated
    _ = rules
    by_rule = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    doc = {
        "version": REPORT_VERSION,
        "findings": findings_to_dicts(findings),
        "suppressed": findings_to_dicts(suppressed),
        "stale_suppressions": list(stale),
        "rules": dict(sorted(RULE_DOCS.items())),
        "summary": {"findings": len(findings),
                    "suppressed": len(suppressed),
                    "by_rule": dict(sorted(by_rule.items()))},
    }
    return json.dumps(doc, indent=indent)


def render_text(findings, suppressed=(), stale=(), fix_hints=False):
    lines = []
    for f in findings:
        lines.append(f"{f.file}:{f.line}: {f.rule} [{f.reason_code}] "
                     f"{f.message}"
                     + (f"  (in `{f.symbol}`)" if f.symbol else ""))
        if fix_hints:
            hint = _rule_hint(f.rule)
            if hint:
                lines.append(f"    fix: {hint}")
    if suppressed:
        lines.append(f"{len(suppressed)} finding(s) suppressed by "
                     "baseline:")
        for f in suppressed:
            lines.append(f"  - {f.file}:{f.line}: {f.rule} {f.message}")
    for e in stale:
        lines.append(
            f"STALE suppression ({e.get('rule')} {e.get('file')} "
            f"`{e.get('symbol')}`): no matching finding — the violation "
            "was fixed; remove the entry (or --write-baseline)")
    n = len(findings)
    lines.append(f"fusion_lint: {n} unsuppressed finding(s)"
                 + (f", {len(suppressed)} suppressed" if suppressed
                    else "")
                 + (f", {len(stale)} stale suppression(s)" if stale
                    else ""))
    return "\n".join(lines)
