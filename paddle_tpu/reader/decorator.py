"""Reader-creator combinators (reference: python/paddle/reader/decorator.py).

The thread-backed pieces (buffered :301, xmap_readers :408,
multiprocess_reader :504) keep the reference's queue/end-signal protocol but
use threads throughout — host-side ingest parallelism on a TPU VM is
IO-bound, and threads avoid the fork-vs-JAX deadlock (multiprocessing is
reserved for the DataLoader worker pool, paddle_tpu.io).
"""
from __future__ import annotations

import itertools
import random as _random
import threading
import time
import queue as _queue

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader",
           "ComposeNotAligned"]


def cache(reader):
    """Cache the reader's full output in memory on first pass
    (reference decorator.py:47)."""
    all_data = []
    filled = []

    def cache_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)
    return cache_reader


def map_readers(func, *readers):
    """Zip several readers, mapping func over the per-reader samples
    (reference decorator.py:87)."""
    def reader():
        rs = [r() for r in readers]
        for elems in zip(*rs):
            yield func(*elems)
    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: fill a buf_size window, emit it shuffled
    (reference decorator.py:129)."""
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return data_reader


def chain(*readers):
    """Concatenate readers back to back (reference decorator.py:178)."""
    def reader():
        return itertools.chain(*[r() for r in readers])
    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples; check_alignment (default True)
    raises ComposeNotAligned when one ends early
    (reference decorator.py:243)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
            return
        for outputs in itertools.zip_longest(*rs):
            if any(o is None for o in outputs):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield sum(map(make_tuple, outputs), ())
    return reader


def buffered(reader, size):
    """Read ahead into a bounded queue on a worker thread
    (reference decorator.py:301)."""
    class _End:
        pass

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)

        def read_worker():
            for d in r:
                q.put(d)
            q.put(_End())

        t = threading.Thread(target=read_worker, daemon=True)
        t.start()
        e = q.get()
        while not isinstance(e, _End):
            yield e
            e = q.get()
    return data_reader


def firstn(reader, n):
    """Limit to the first n samples (reference decorator.py:363)."""
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return firstn_reader


class XmapEndSignal:
    pass


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with process_num workers
    (reference decorator.py:408 — same in/out queue + end-signal protocol,
    thread workers here)."""
    end = XmapEndSignal()

    def read_worker(r, in_q):
        for i in r:
            in_q.put(i)
        in_q.put(end)

    def order_read_worker(r, in_q):
        for i, sample in enumerate(r):
            in_q.put((i, sample))
        in_q.put(end)

    def handle_worker(in_q, out_q, fn):
        sample = in_q.get()
        while not isinstance(sample, XmapEndSignal):
            out_q.put(fn(sample))
            sample = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def order_handle_worker(in_q, out_q, fn, out_order):
        ins = in_q.get()
        while not isinstance(ins, XmapEndSignal):
            order_id, sample = ins
            result = fn(sample)
            while order_id != out_order[0]:
                time.sleep(0.001)   # yield the GIL to the draining thread
            out_q.put(result)
            out_order[0] += 1
            ins = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        out_order = [0]
        target = order_read_worker if order else read_worker
        t = threading.Thread(target=target, args=(reader(), in_q),
                             daemon=True)
        t.start()
        args = (in_q, out_q, mapper, out_order) if order else \
            (in_q, out_q, mapper)
        workers = []
        for _ in range(process_num):
            w = threading.Thread(
                target=order_handle_worker if order else handle_worker,
                args=args, daemon=True)
            w.start()
            workers.append(w)
        finish = 0
        while finish < process_num:
            sample = out_q.get()
            if isinstance(sample, XmapEndSignal):
                finish += 1
            else:
                yield sample
    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave several readers concurrently
    (reference decorator.py:504; thread-backed here — see module note)."""
    if len(readers) < 1:
        raise ValueError("multiprocess_reader needs at least one reader")

    def queue_reader():
        q = _queue.Queue(queue_size)

        def worker(r):
            for sample in r():
                q.put(sample)
            q.put(None)

        for r in readers:
            threading.Thread(target=worker, args=(r,), daemon=True).start()
        finish = 0
        while finish < len(readers):
            sample = q.get()
            if sample is None:
                finish += 1
            else:
                yield sample
    return queue_reader
