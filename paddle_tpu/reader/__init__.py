"""paddle.reader — reader-creator decorators.

Reference analog: python/paddle/reader/decorator.py. A *reader creator* is a
zero-arg callable returning an iterable of samples; these combinators wrap
creators into new creators (shuffle/buffer/compose/...). Kept as plain host
Python: readers feed the host side of the input pipeline and never trace.
"""
from .decorator import (  # noqa: F401
    cache, map_readers, shuffle, chain, compose, buffered, firstn,
    xmap_readers, multiprocess_reader, ComposeNotAligned,
)

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader",
           "ComposeNotAligned"]
