"""paddle.metric equivalent. Reference analog: python/paddle/metric/metrics.py
(Metric base, Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp
    from ..ops._helpers import ensure_tensor
    pred = ensure_tensor(input)._value
    lab = ensure_tensor(label)._value
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    topk_idx = jnp.argsort(pred, axis=-1)[..., ::-1][..., :k]
    match = (topk_idx == lab[..., None]).any(axis=-1)
    return Tensor(jnp.mean(match.astype(jnp.float32)))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred)
        lab = np.asarray(label)
        if lab.ndim == pred_np.ndim:
            lab = lab.squeeze(-1)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = idx == lab[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct)
        n = c.shape[0]
        res = []
        for i, k in enumerate(self.topk):
            num = float(c[..., :k].sum())
            self.total[i] += num
            self.count[i] += n
            res.append(num / n if n else 0.0)
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = np.asarray(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return auc / denom if denom else 0.0

    def name(self):
        return self._name
