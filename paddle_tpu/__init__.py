"""paddle_tpu — a TPU-native deep-learning framework with the PaddlePaddle
capability surface, built on jax/XLA/Pallas.

Architecture (vs the reference layer map, SURVEY.md §1):
  - compute path: ops lower to XLA; hot fused ops are Pallas kernels
  - autograd: define-by-run tape capturing jax VJPs (framework/autograd.py)
  - static mode / jit: trace-to-jaxpr + jax.jit (paddle_tpu.jit)
  - distributed: jax.sharding.Mesh + collectives over ICI/DCN
    (paddle_tpu.distributed)
"""
from __future__ import annotations

import sys as _sys

__version__ = "0.1.0"

# deep transformer stacks exceed the default interpreter recursion limit
# during jax tracing/linearization
if _sys.getrecursionlimit() < 10000:
    _sys.setrecursionlimit(10000)

from .framework import (  # noqa: F401
    Tensor, Parameter, to_tensor, is_tensor, Place,
    no_grad, enable_grad, set_grad_enabled, is_grad_enabled, grad,
    seed, set_default_dtype, get_default_dtype,
    uint8, int8, int16, int32, int64, float16, bfloat16, float32, float64,
    complex64, complex128,
)
from .framework import bool_ as bool  # noqa: F401  (paddle.bool)
from .framework.dtype import convert_dtype  # noqa: F401

from .ops import *  # noqa: F401,F403
from .ops import add_n  # noqa: F401
from . import ops  # noqa: F401

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import device  # noqa: F401
from . import distributed  # noqa: F401
from . import vision  # noqa: F401
from . import metric  # noqa: F401
from . import profiler  # noqa: F401
from . import incubate  # noqa: F401
from . import static  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import autograd  # noqa: F401
from . import distribution  # noqa: F401
from . import geometric  # noqa: F401
from . import text  # noqa: F401
from . import audio  # noqa: F401
from . import quantization  # noqa: F401
from . import sysconfig  # noqa: F401
from . import onnx  # noqa: F401
from . import signal  # noqa: F401
from . import sparse  # noqa: F401
from . import fft  # noqa: F401
from . import linalg  # noqa: F401
from . import utils  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import cost_model  # noqa: F401

from .framework.io import save, load  # noqa: F401
from .device import (  # noqa: F401
    set_device, get_device, CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace,
    NPUPlace, XPUPlace, MLUPlace, IPUPlace,
)
from .jit import to_static  # noqa: F401

from .framework.dtype import DType as dtype, iinfo, finfo  # noqa: F401
from .framework.lazy import LazyGuard  # noqa: F401
from .framework.random import (  # noqa: F401
    get_rng_state, set_rng_state, get_cuda_rng_state, set_cuda_rng_state,
)
from .batch import batch  # noqa: F401
from .nn.initializer_util import ParamAttr  # noqa: F401
from .distributed import DataParallel  # noqa: F401


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """`paddle.create_parameter` — a free-standing trainable Parameter.
    Reference analog: python/paddle/tensor/creation.py create_parameter
    (LayerHelper.create_parameter)."""
    from .nn.initializer_util import materialize_parameter
    p = materialize_parameter(shape, attr=attr, dtype=dtype, is_bias=is_bias,
                              default_initializer=default_initializer)
    if name is not None:
        p.name = name
    return p


def check_shape(shape):
    """Validate a shape argument (reference:
    python/paddle/fluid/data_feeder.py:185 check_shape)."""
    if isinstance(shape, Tensor):
        return
    if not isinstance(shape, (list, tuple)):
        raise TypeError(f"shape must be a list/tuple/Tensor, got {shape!r}")
    for s in shape:
        if not isinstance(s, (int, Tensor)):
            raise TypeError(f"shape elements must be int/Tensor, got {s!r}")
        if isinstance(s, int) and s < -1:
            raise ValueError(f"invalid dimension {s} in shape {shape}")


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """`paddle.flops` — see hapi.dynamic_flops.flops."""
    from .hapi.dynamic_flops import flops as _flops
    return _flops(net, input_size=input_size, inputs=inputs,
                  custom_ops=custom_ops, print_detail=print_detail)

# paddle.disable_static / enable_static parity: dygraph is the default mode
_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static(place=None):
    global _static_mode
    _static_mode = False


def in_dynamic_mode():
    return not _static_mode


def disable_signal_handler():
    pass


def get_flags(flags):
    from .framework import flags as _f
    return _f.get_flags(flags)


def set_flags(flags):
    from .framework import flags as _f
    return _f.set_flags(flags)


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes)


from . import hapi  # noqa: F401,E402
from .hapi import Model  # noqa: F401,E402
from .hapi import callbacks  # noqa: F401,E402

# Live HTTP observability plane (profiler/telemetry_server.py): a process
# launched with FLAGS_telemetry_port set (env-seeded like every flag)
# answers /metrics, /goodput, /doctor, /healthz, /readyz from the moment
# the framework imports. One dict lookup when the flag is 0 (default).
from .profiler import telemetry_server as _telemetry_server  # noqa: E402
_telemetry_server.maybe_start_from_flags()

# Performance regression sentinel (profiler/sentinel.py): FLAGS_sentinel=1
# arms the per-window drift watcher the same way. One bool check per
# step-boundary / decode-step tick when disarmed (default).
from .profiler import sentinel as _sentinel  # noqa: E402
_sentinel.maybe_arm_from_flags()
