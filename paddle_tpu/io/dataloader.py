"""DataLoader with threaded prefetch and multiprocess workers.

Reference analog: python/paddle/fluid/reader.py:312 (DataLoader),
fluid/dataloader/dataloader_iter.py (_DataLoaderIterMultiProcess: index
queue -> worker subprocesses -> reorder-by-batch-index), and the C++
double-buffering reader (operators/reader/buffered_reader.cc).

TPU-first: with num_workers > 0 batches are assembled in worker PROCESSES
started via a FORKSERVER (numpy-only in the children — a worker must never
touch the parent's initialized XLA runtime, and forking the multithreaded
JAX parent directly is a deadlock hazard the reference avoids with
spawn-capable worker plumbing), reordered by batch index in the parent, and
staged through a bounded prefetch queue so host input processing overlaps
device compute. Device transfer happens lazily on first use (jnp.asarray),
which XLA pipelines.
"""
from __future__ import annotations

import itertools
import multiprocessing
import queue
import threading
from time import monotonic as _monotonic

import numpy as np

from ..framework.core import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn", "get_worker_info", "WorkerInfo"]


def _np_collate(batch):
    """Numpy-only collation for worker processes (no jax in forked
    children)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        # unwrap to host numpy — a forked child must not run jax ops, but
        # np.asarray on an existing device buffer is a read
        batch = [np.asarray(b._value) for b in batch]
        sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(_np_collate(list(items)) for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    return batch


def _to_tensors(data):
    if isinstance(data, np.ndarray):
        return Tensor(data)
    if isinstance(data, tuple):
        return tuple(_to_tensors(d) for d in data)
    if isinstance(data, dict):
        return {k: _to_tensors(v) for k, v in data.items()}
    return data


def _worker_loop(dataset, task_q, result_q, worker_id, worker_init_fn,
                 raw_samples, num_workers=0, base_seed=0):
    """Body of one worker subprocess (reference:
    dataloader_iter.py _worker_loop). Pulls (batch_idx, indices), pushes
    (batch_idx, payload) — numpy only."""
    global _worker_info
    # per-worker distinct seed (reference: base_seed + worker_id), so
    # random augmentations differ across workers but are reproducible for
    # a given worker index (base_seed derives from the framework seed, not
    # time/pid)
    seed = (base_seed + worker_id) % (2 ** 31)
    _worker_info = WorkerInfo(worker_id, num_workers, dataset, seed=seed)
    np.random.seed(seed)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        task = task_q.get()
        if task is None:
            return
        bidx, indices = task
        try:
            samples = [dataset[i] for i in indices]
            payload = samples if raw_samples else _np_collate(samples)
            result_q.put((bidx, payload, None))
        except BaseException as e:       # ship the error to the parent
            result_q.put((bidx, None, f"{type(e).__name__}: {e}"))


_WORKER_CTX = None
# monotonic epoch counter feeding per-producer base seeds (deterministic,
# unlike SeedSequence entropy)
_epoch_counter = itertools.count()


def _worker_context():
    """Worker process context. Forking the parent is unsafe once JAX's
    runtime threads exist (CPython 3.12 warns it may deadlock), so workers
    come from a FORKSERVER: one clean server process preloads this module
    (paying the import once), then forks cheap numpy-only children from
    its single-threaded state. Falls back to spawn where forkserver is
    unavailable. Reference analog: fluid/dataloader/dataloader_iter.py's
    spawn-capable worker plumbing."""
    global _WORKER_CTX
    if _WORKER_CTX is None:
        try:
            ctx = multiprocessing.get_context("forkserver")
            ctx.set_forkserver_preload(["paddle_tpu.io.dataloader"])
        except ValueError:                        # platform without it
            ctx = multiprocessing.get_context("spawn")
        _WORKER_CTX = ctx
    return _WORKER_CTX


class _MultiprocessProducer:
    """Fan out index batches to forked workers; yield results IN ORDER.

    In-flight work is windowed to num_workers * prefetch_factor batches
    (like the reference _DataLoaderIterMultiProcess outstanding-batch
    cap), so a slow consumer doesn't let workers race through the epoch
    and pile every collated batch into host memory."""

    def __init__(self, dataset, batches, num_workers, worker_init_fn,
                 timeout, raw_samples, prefetch_factor=2):
        ctx = _worker_context()
        self._task_q = ctx.SimpleQueue()
        self._result_q = ctx.Queue()
        self._timeout = timeout
        self._depth = max(1, num_workers * max(prefetch_factor, 1))
        self._workers = []
        # deterministic per-worker seeding: a SEEDED program (paddle.seed)
        # derives the base seed from the framework seed plus an epoch
        # counter — NOT from time/pid entropy — so worker k's augmentation
        # stream is reproducible run-to-run; an unseeded program keeps
        # per-run entropy (independent hyper-parameter workers must not
        # all see the same "random" augmentations)
        from ..framework.random import default_generator
        if default_generator.seeded:
            base_seed = (int(default_generator.initial_seed) * 1000003
                         + next(_epoch_counter) * 10007) % (2 ** 31)
        else:
            base_seed = int(np.random.SeedSequence().entropy % (2 ** 31))
        for w in range(num_workers):
            p = ctx.Process(target=_worker_loop,
                            args=(dataset, self._task_q, self._result_q, w,
                                  worker_init_fn, raw_samples, num_workers,
                                  base_seed),
                            daemon=True)
            p.start()
            self._workers.append(p)
        self._batches = list(batches)

    def _get_result(self):
        """Wait for one result, polling worker liveness (a SIGKILLed or
        fork-deadlocked worker must surface as an error, not a hang)."""
        import time as _time
        deadline = (_time.monotonic() + self._timeout) if self._timeout \
            else None
        while True:
            try:
                return self._result_q.get(timeout=1.0)
            except queue.Empty:
                if any(not p.is_alive() for p in self._workers):
                    raise RuntimeError(
                        "a DataLoader worker process died unexpectedly "
                        "(killed or crashed before reporting)") from None
                if deadline is not None and _time.monotonic() > deadline:
                    raise RuntimeError(
                        f"DataLoader worker timed out after "
                        f"{self._timeout}s") from None

    def __iter__(self):
        try:
            n = len(self._batches)
            submitted = 0
            while submitted < min(self._depth, n):
                self._task_q.put((submitted,
                                  list(self._batches[submitted])))
                submitted += 1
            pending = {}
            for want in range(n):
                while want not in pending:
                    bidx, payload, err = self._get_result()
                    if err is not None:
                        raise RuntimeError(
                            f"DataLoader worker failed on batch {bidx}: "
                            f"{err}")
                    pending[bidx] = payload
                    if submitted < n:
                        self._task_q.put(
                            (submitted, list(self._batches[submitted])))
                        submitted += 1
                yield pending.pop(want)
        finally:
            self.close()

    def close(self):
        # graceful first: sentinels let a worker still inside startup run
        # its worker_init_fn and exit cleanly (terminate() could kill it
        # BEFORE init ran — the old worker_init flake); stragglers are
        # terminated after a bounded join
        for _ in self._workers:
            try:
                self._task_q.put(None)
            except Exception:
                break
        deadline = _monotonic() + 5.0
        for p in self._workers:
            p.join(timeout=max(0.1, deadline - _monotonic()))
        for p in self._workers:
            if p.is_alive():
                p.terminate()
        for p in self._workers:
            p.join(timeout=1.0)
        self._workers = []


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        from ..core import parallel_collate
        return Tensor(parallel_collate(batch))
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._value for b in batch]))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _PrefetchIterator:
    """Producer thread fills a bounded queue; blocking/wakeup runs in the
    native core's BoundedQueue (reference: buffered_reader.cc +
    lod_tensor_blocking_queue.h) with a queue.Queue fallback."""

    def __init__(self, produce_batches, prefetch=2):
        from ..core import BoundedQueue
        self._q = BoundedQueue(max(prefetch, 1))
        self._exc = None
        self._thread = threading.Thread(target=self._run,
                                        args=(produce_batches,), daemon=True)
        self._thread.start()

    def _run(self, produce_batches):
        try:
            for b in produce_batches():
                if not self._q.push(b):
                    return  # consumer closed the queue
        except BaseException as e:  # propagate to consumer
            self._exc = e
        finally:
            self._q.close()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return self._q.pop()
        except StopIteration:
            if self._exc is not None:
                raise self._exc from None
            raise

    def close(self):
        """Wake a blocked producer and join it; must run before the native
        queue is freed (an abandoned producer blocked in push would
        otherwise race queue destruction)."""
        self._q.close()
        self._thread.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self._custom_collate = collate_fn is not None
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def _produce(self):
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if self.batch_size and len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.num_workers > 0 and hasattr(multiprocessing, "get_context"):
            # subprocess workers (reference _DataLoaderIterMultiProcess).
            # Default collate: workers collate numpy, the parent wraps
            # Tensors. Custom collate_fn runs in the PARENT on the raw
            # samples (jax must never run in a forked child).
            raw = self._custom_collate
            producer = _MultiprocessProducer(
                self.dataset, iter(self.batch_sampler), self.num_workers,
                self.worker_init_fn, self.timeout, raw,
                prefetch_factor=self.prefetch_factor)
            for payload in producer:
                yield self.collate_fn(payload) if raw \
                    else _to_tensors(payload)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.use_buffer_reader:
            return _PrefetchIterator(self._produce,
                                     prefetch=self.prefetch_factor)
        return self._produce()


class WorkerInfo:
    """Reference: fluid/dataloader/worker.py WorkerInfo — identifies the
    current DataLoader worker process."""

    def __init__(self, id, num_workers, dataset, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


_worker_info = None


def get_worker_info():
    """Inside a DataLoader worker: its WorkerInfo; in the main process:
    None (reference: fluid/dataloader/worker.py get_worker_info)."""
    return _worker_info
