"""DataLoader with threaded prefetch.

Reference analog: python/paddle/fluid/reader.py:312 (DataLoader),
fluid/dataloader/dataloader_iter.py (worker iterators), and the C++
double-buffering reader (operators/reader/buffered_reader.cc).

TPU-first: batches are assembled by a thread pool (numpy is GIL-releasing for
the copy-heavy parts) and staged through a bounded prefetch queue so host input
processing overlaps device compute. Device transfer happens lazily on first
use (jnp.asarray), which XLA pipelines.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ..framework.core import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        from ..core import parallel_collate
        return Tensor(parallel_collate(batch))
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._value for b in batch]))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(items)) for items in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class _PrefetchIterator:
    """Producer thread fills a bounded queue; blocking/wakeup runs in the
    native core's BoundedQueue (reference: buffered_reader.cc +
    lod_tensor_blocking_queue.h) with a queue.Queue fallback."""

    def __init__(self, produce_batches, prefetch=2):
        from ..core import BoundedQueue
        self._q = BoundedQueue(max(prefetch, 1))
        self._exc = None
        self._thread = threading.Thread(target=self._run,
                                        args=(produce_batches,), daemon=True)
        self._thread.start()

    def _run(self, produce_batches):
        try:
            for b in produce_batches():
                if not self._q.push(b):
                    return  # consumer closed the queue
        except BaseException as e:  # propagate to consumer
            self._exc = e
        finally:
            self._q.close()

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return self._q.pop()
        except StopIteration:
            if self._exc is not None:
                raise self._exc from None
            raise

    def close(self):
        """Wake a blocked producer and join it; must run before the native
        queue is freed (an abandoned producer blocked in push would
        otherwise race queue destruction)."""
        self._q.close()
        self._thread.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def _produce(self):
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if self.batch_size and len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.num_workers > 0:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(self.num_workers) as pool:
                def fetch(indices):
                    return self.collate_fn(
                        [self.dataset[i] for i in indices])
                # windowed map keeps at most num_workers*prefetch futures alive
                futures = []
                it = iter(self.batch_sampler)
                depth = self.num_workers * max(self.prefetch_factor, 1)
                try:
                    for _ in range(depth):
                        futures.append(pool.submit(fetch, next(it)))
                except StopIteration:
                    it = None
                while futures:
                    yield futures.pop(0).result()
                    if it is not None:
                        try:
                            futures.append(pool.submit(fetch, next(it)))
                        except StopIteration:
                            it = None
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.use_buffer_reader:
            return _PrefetchIterator(self._produce,
                                     prefetch=self.prefetch_factor)
        return self._produce()
