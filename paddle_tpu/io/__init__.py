"""Data loading. Reference analog: python/paddle/fluid/reader.py:312
(DataLoader), fluid/dataloader/ (Dataset, samplers, multiprocess iter), and the
C++ buffered_reader (operators/reader/buffered_reader.cc) for device
double-buffering.

TPU-first: workers produce numpy batches on host threads; a prefetch queue
overlaps host batch assembly with device compute (the buffered_reader role).
"""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    Subset, random_split,
)
from .sampler import (  # noqa: F401
    Sampler, SequenceSampler, RandomSampler, BatchSampler,
    DistributedBatchSampler, WeightedRandomSampler,
)
from .dataloader import (  # noqa: F401
    DataLoader, default_collate_fn, get_worker_info, WorkerInfo)
