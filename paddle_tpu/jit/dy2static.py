"""dy2static: AST conversion of data-dependent Python control flow.

Reference analog: python/paddle/fluid/dygraph/dygraph_to_static/
(program_translator.py + ifelse_transformer.py / loop_transformer.py) — the
reference rewrites `if`/`while` over tensors into cond/while ops in the
ProgramDesc. TPU-first: the same AST rewrite targets `lax.cond` /
`lax.while_loop`, so a data-dependent branch or loop compiles into the ONE
jitted program instead of failing the trace.

Scope (the pragmatic subset the transformer guarantees):
  - `if`/`while` whose condition may be a traced Tensor;
  - branch/loop bodies that communicate through assigned local variables
    (the transformer computes the carried-name set);
  - bodies containing `return`/`break`/`continue` are left untransformed
    (python semantics; they only work with concrete conditions);
  - python-valued conditions keep exact python semantics (the runtime
    helpers fall back to ordinary branching when the predicate is concrete).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["convert_ifelse", "convert_while", "ast_transform",
           "Dy2StaticError"]


class Dy2StaticError(RuntimeError):
    pass


def _raw(v):
    return v._value if isinstance(v, Tensor) else v


def _is_tracer(v):
    return isinstance(v, jax.core.Tracer)


def _pack(carry):
    """Split a carry tuple into (traced values, rebuild) — Tensor/array
    leaves flow through lax; everything else is static and passes through
    unchanged (branches must not rewrite statics divergently)."""
    vals, slots = [], []
    for c in carry:
        r = _raw(c)
        if isinstance(r, (jax.Array, jnp.ndarray)) or _is_tracer(r) or \
                isinstance(r, (int, float, bool)):
            slots.append(len(vals))
            vals.append(jnp.asarray(r))
        else:
            slots.append(None)

    def rebuild(new_vals, statics=carry):
        out = []
        for slot, orig in zip(slots, statics):
            if slot is None:
                out.append(orig)
            else:
                out.append(Tensor(new_vals[slot], stop_gradient=True))
        return tuple(out)
    return tuple(vals), rebuild, slots


def convert_ifelse(pred, true_fn, false_fn, carry):
    """Runtime of a transformed `if`: python branch for concrete predicates,
    lax.cond for traced ones. The OUTPUT structure is read off the branch
    traces (lax.cond traces both branches at bind time), so locals first
    bound inside the branches work."""
    p = _raw(pred)
    if not _is_tracer(p):
        return true_fn(*carry) if bool(p) else false_fn(*carry)
    vals, rebuild, _ = _pack(carry)
    meta = {}

    def wrap(fn, tag):
        def g(vs):
            out = fn(*rebuild(vs))
            ovals, _, oslots = _pack(out)
            meta[tag] = (oslots, out)
            return ovals
        return g

    try:
        out_vals = jax.lax.cond(jnp.asarray(p, bool).reshape(()),
                                wrap(true_fn, "t"), wrap(false_fn, "f"),
                                vals)
    except TypeError as e:
        raise Dy2StaticError(
            "branches of a traced `if` must produce matching tensor "
            f"structures: {e}") from None
    if meta["t"][0] != meta["f"][0]:
        raise Dy2StaticError(
            "branches of a traced `if` must bind the same set of "
            "tensor-valued locals")
    oslots, sample = meta["t"]
    return tuple(sample[i] if slot is None
                 else Tensor(out_vals[slot], stop_gradient=True)
                 for i, slot in enumerate(oslots))


def convert_while(cond_fn, body_fn, carry):
    """Runtime of a transformed `while`: python loop for concrete
    predicates, lax.while_loop once the condition traces."""
    first = _raw(cond_fn(*carry))
    if not _is_tracer(first):
        # concrete: plain python loop (re-evaluating the condition eagerly)
        while bool(_raw(cond_fn(*carry))):
            carry = body_fn(*carry)
        return carry
    vals, rebuild, slots = _pack(carry)

    def cond(vs):
        return jnp.asarray(_raw(cond_fn(*rebuild(vs))), bool).reshape(())

    def body(vs):
        out = body_fn(*rebuild(vs))
        ovals, _, oslots = _pack(out)
        if oslots != slots:
            raise Dy2StaticError(
                "a traced `while` body must keep the same set of "
                "tensor-valued locals as the loop entry (bind loop "
                "variables before the loop)")
        return ovals

    out_vals = jax.lax.while_loop(cond, body, vals)
    return rebuild(out_vals)


# ---------------------------------------------------------------------------
# AST transformation
# ---------------------------------------------------------------------------

class _AssignedNames(ast.NodeVisitor):
    """Names assigned (Store context) in a statement list, not descending
    into nested function/class scopes."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _has_flow_escape(stmts):
    """True if the statement list contains top-scope return/break/continue
    (not inside a nested function or a nested loop for break/continue)."""
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, node):
            self.found = True

        def visit_Break(self, node):
            self.found = True

        def visit_Continue(self, node):
            self.found = True

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

        # break/continue inside a NESTED loop don't escape our region, but a
        # nested loop's body may still contain `return`; keep scanning loops.
    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _names_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


class _Undefined:
    """Sentinel for locals not yet bound when a transformed region starts
    (reference analog: dygraph_to_static UndefinedVar)."""

    def __repr__(self):
        return "<dy2static undefined>"


UNDEFINED = _Undefined()


def _undef_guard(name):
    """`try: name\nexcept NameError|UnboundLocalError: name = UNDEFINED`"""
    return ast.Try(
        body=[ast.Expr(value=ast.Name(id=name, ctx=ast.Load()))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(elts=[ast.Name(id="NameError", ctx=ast.Load()),
                                 ast.Name(id="UnboundLocalError",
                                          ctx=ast.Load())],
                           ctx=ast.Load()),
            name=None,
            body=[ast.Assign(
                targets=[ast.Name(id=name, ctx=ast.Store())],
                value=ast.Name(id="_d2s_UNDEFINED", ctx=ast.Load()))])],
        orelse=[], finalbody=[])


class _CtrlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._k = 0

    def _fresh(self, kind):
        self._k += 1
        return f"_d2s_{kind}_{self._k}"

    def _make_fn(self, name, arg_names, body, ret_names):
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in arg_names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        ret = ast.Return(value=_names_tuple(ret_names, ast.Load))
        return ast.FunctionDef(name=name, args=args, body=body + [ret],
                               decorator_list=[], returns=None,
                               type_params=[])

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            return node
        names = sorted(_assigned(node.body) | _assigned(node.orelse))
        if not names:
            return node
        tname = self._fresh("true")
        fname = self._fresh("false")
        tfn = self._make_fn(tname, names, node.body, names)
        ffn = self._make_fn(fname, names,
                            node.orelse if node.orelse else [ast.Pass()],
                            names)
        call = ast.Call(
            func=ast.Name(id="_d2s_convert_ifelse", ctx=ast.Load()),
            args=[node.test, ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  _names_tuple(names, ast.Load)], keywords=[])
        assign = ast.Assign(targets=[_names_tuple(names, ast.Store)],
                            value=call)
        return [_undef_guard(n) for n in names] + [tfn, ffn, assign]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_flow_escape(node.body):
            return node
        names = sorted(_assigned(node.body))
        if not names:
            return node
        cname = self._fresh("cond")
        bname = self._fresh("body")
        cargs = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        cfn = ast.FunctionDef(
            name=cname, args=cargs, body=[ast.Return(value=node.test)],
            decorator_list=[], returns=None, type_params=[])
        bfn = self._make_fn(bname, names, node.body, names)
        call = ast.Call(
            func=ast.Name(id="_d2s_convert_while", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  _names_tuple(names, ast.Load)], keywords=[])
        assign = ast.Assign(targets=[_names_tuple(names, ast.Store)],
                            value=call)
        return [_undef_guard(n) for n in names] + [cfn, bfn, assign]


def ast_transform(func):
    """Rewrite `func`'s data-dependent if/while into convert_ifelse /
    convert_while calls; returns the transformed function, or None when the
    function can't be transformed (no source, closures)."""
    raw = getattr(func, "__func__", func)
    if getattr(raw, "__closure__", None):
        return None
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return None
    fndef = tree.body[0]
    if not isinstance(fndef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fndef.decorator_list = []
    new_tree = _CtrlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    ns = dict(raw.__globals__)
    ns["_d2s_convert_ifelse"] = convert_ifelse
    ns["_d2s_convert_while"] = convert_while
    ns["_d2s_UNDEFINED"] = UNDEFINED
    code = compile(new_tree, filename=f"<dy2static:{raw.__name__}>",
                   mode="exec")
    exec(code, ns)
    new_fn = ns[fndef.name]
    new_fn.__dy2static__ = True
    return new_fn
