"""dy2static: AST conversion of data-dependent Python control flow.

Reference analog: python/paddle/fluid/dygraph/dygraph_to_static/
(program_translator.py + ifelse_transformer.py / loop_transformer.py) — the
reference rewrites `if`/`while` over tensors into cond/while ops in the
ProgramDesc. TPU-first: the same AST rewrite targets `lax.cond` /
`lax.while_loop`, so a data-dependent branch or loop compiles into the ONE
jitted program instead of failing the trace.

Scope (the pragmatic subset the transformer guarantees):
  - `if`/`while` whose condition may be a traced Tensor;
  - `for` over `range(...)` with possibly-traced bounds (lowered to a
    lax.while_loop with fori semantics) and `for` over a Tensor (iterates
    the leading axis; static trip count, dynamic indexing);
  - `break`/`continue` in `for`/`while` bodies, lowered to carried boolean
    flags with guarded tails (reference analog: loop_transformer.py +
    break_continue_transformer.py);
  - branch/loop bodies that communicate through assigned local variables
    (the transformer computes the carried-name set);
  - bodies containing `return` are left untransformed (python semantics;
    they only work with concrete conditions);
  - python-valued conditions/bounds keep exact python semantics (the
    runtime helpers fall back to ordinary branching/looping when the
    predicate is concrete).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["convert_ifelse", "convert_while", "convert_range_for",
           "convert_iter_for", "ast_transform", "Dy2StaticError"]


class Dy2StaticError(RuntimeError):
    pass


def _raw(v):
    return v._value if isinstance(v, Tensor) else v


def _is_tracer(v):
    return isinstance(v, jax.core.Tracer)


def _pack(carry):
    """Split a carry tuple into (traced values, rebuild) — Tensor/array
    leaves flow through lax; everything else is static and passes through
    unchanged (branches must not rewrite statics divergently)."""
    vals, slots = [], []
    for c in carry:
        r = _raw(c)
        if isinstance(r, (jax.Array, jnp.ndarray)) or _is_tracer(r) or \
                isinstance(r, (int, float, bool)):
            slots.append(len(vals))
            vals.append(jnp.asarray(r))
        else:
            slots.append(None)

    def rebuild(new_vals, statics=carry):
        out = []
        for slot, orig in zip(slots, statics):
            if slot is None:
                out.append(orig)
            else:
                out.append(Tensor(new_vals[slot], stop_gradient=True))
        return tuple(out)
    return tuple(vals), rebuild, slots


def convert_ifelse(pred, true_fn, false_fn, carry):
    """Runtime of a transformed `if`: python branch for concrete predicates,
    lax.cond for traced ones. The OUTPUT structure is read off the branch
    traces (lax.cond traces both branches at bind time), so locals first
    bound inside the branches work."""
    p = _raw(pred)
    if not _is_tracer(p):
        return true_fn(*carry) if bool(p) else false_fn(*carry)
    vals, rebuild, _ = _pack(carry)
    meta = {}

    def wrap(fn, tag):
        def g(vs):
            out = fn(*rebuild(vs))
            ovals, _, oslots = _pack(out)
            meta[tag] = (oslots, out)
            return ovals
        return g

    try:
        out_vals = jax.lax.cond(jnp.asarray(p, bool).reshape(()),
                                wrap(true_fn, "t"), wrap(false_fn, "f"),
                                vals)
    except TypeError as e:
        raise Dy2StaticError(
            "branches of a traced `if` must produce matching tensor "
            f"structures: {e}") from None
    if meta["t"][0] != meta["f"][0]:
        raise Dy2StaticError(
            "branches of a traced `if` must bind the same set of "
            "tensor-valued locals")
    oslots, sample = meta["t"]
    return tuple(sample[i] if slot is None
                 else Tensor(out_vals[slot], stop_gradient=True)
                 for i, slot in enumerate(oslots))


def convert_while(cond_fn, body_fn, carry):
    """Runtime of a transformed `while`: python loop for concrete
    predicates, lax.while_loop once the condition traces — including a
    condition that only BECOMES traced mid-loop (e.g. a lowered break flag
    fed by traced data), in which case the loop restarts traced (the
    partial python trace is dead code XLA eliminates)."""
    carry0 = tuple(carry)
    first = _raw(cond_fn(*carry))
    if not _is_tracer(first):
        # concrete: plain python loop (re-evaluating the condition eagerly)
        while True:
            c = _raw(cond_fn(*carry))
            if _is_tracer(c):
                carry = carry0
                break
            if not bool(c):
                return carry
            carry = body_fn(*carry)
    vals, rebuild, slots = _pack(carry)

    def cond(vs):
        return jnp.asarray(_raw(cond_fn(*rebuild(vs))), bool).reshape(())

    def body(vs):
        out = body_fn(*rebuild(vs))
        ovals, _, oslots = _pack(out)
        if oslots != slots:
            raise Dy2StaticError(
                "a traced `while` body must keep the same set of "
                "tensor-valued locals as the loop entry (bind loop "
                "variables before the loop)")
        return ovals

    out_vals = jax.lax.while_loop(cond, body, vals)
    return rebuild(out_vals)


def and_not_flag(flag, cond_thunk):
    """`(not flag) and cond()` that stays lazily short-circuit for concrete
    flags and lowers to logical ops for traced ones (used as the loop
    condition of a `while` containing `break`)."""
    f = _raw(flag)
    if not _is_tracer(f):
        if bool(f):
            return False
        return cond_thunk()
    c = _raw(cond_thunk())
    return Tensor(jnp.logical_and(
        jnp.logical_not(jnp.asarray(f, bool).reshape(())),
        jnp.asarray(c, bool).reshape(())), stop_gradient=True)


def keep_going(*flags):
    """`not (flag1 or flag2 ...)` — guard for statements following a
    lowered break/continue."""
    rs = [_raw(f) for f in flags]
    if not any(_is_tracer(r) for r in rs):
        return not any(bool(r) for r in rs)
    acc = jnp.zeros((), bool)
    for r in rs:
        acc = jnp.logical_or(acc, jnp.asarray(r, bool).reshape(()))
    return Tensor(jnp.logical_not(acc), stop_gradient=True)


def _traced_loop(trip, item_of, item_seed, body_fn, carry, item_idx,
                 brk_idx):
    """lax.while_loop with fori semantics: k counts 0..trip, the loop
    variable is item_of(k); an optional break flag short-circuits the
    condition. Seeds an unbound loop variable with item_seed so the carry
    structure is stable (the body overwrites it before any read)."""
    carry = list(carry)
    if item_idx is not None and isinstance(carry[item_idx], _Undefined):
        if item_seed is None:
            raise Dy2StaticError(
                "bind the loop variable before a traced `for` whose "
                "iterable may be empty")
        carry[item_idx] = Tensor(jnp.asarray(item_seed), stop_gradient=True)
    vals, rebuild, slots = _pack(tuple(carry))
    brk_slot = slots[brk_idx] if brk_idx is not None else None
    if brk_idx is not None and brk_slot is None:
        raise Dy2StaticError("the lowered break flag must stay boolean")

    def cond(state):
        k, vs = state
        c = jnp.asarray(k < trip, bool).reshape(())
        if brk_slot is not None:
            c = jnp.logical_and(c, jnp.logical_not(
                jnp.asarray(vs[brk_slot], bool).reshape(())))
        return c

    def body(state):
        k, vs = state
        item = Tensor(jnp.asarray(item_of(k)), stop_gradient=True)
        out = body_fn(item, *rebuild(vs))
        ovals, _, oslots = _pack(out)
        if oslots != slots:
            raise Dy2StaticError(
                "a traced `for` body must keep the same set of "
                "tensor-valued locals across iterations (bind loop "
                "variables before the loop)")
        return (k + 1, tuple(ovals))

    _, out_vals = jax.lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), tuple(vals)))
    return rebuild(out_vals)


def convert_range_for(rargs, body_fn, carry, item_idx=None, brk_idx=None):
    """Runtime of a transformed `for ... in range(...)`: python loop for
    concrete bounds; lax.while_loop (fori semantics) when a bound — or a
    data-dependent break flag — traces."""
    if len(rargs) == 1:
        start, stop, step = 0, rargs[0], 1
    elif len(rargs) == 2:
        start, stop, step = rargs[0], rargs[1], 1
    else:
        start, stop, step = rargs
    b0, b1, b2 = (_raw(v) for v in (start, stop, step))
    carry0 = tuple(carry)

    def traced():
        s0, s1, st = (jnp.asarray(b) for b in (b0, b1, b2))
        trip = jnp.maximum(0, (s1 - s0 + st - jnp.sign(st)) // st)
        return _traced_loop(trip, lambda k: s0 + k * st, s0, body_fn,
                            carry0, item_idx, brk_idx)

    if any(_is_tracer(b) for b in (b0, b1, b2)):
        return traced()
    cur = carry0
    for v in range(int(b0), int(b1), int(b2)):
        nxt = body_fn(v, *cur)
        if brk_idx is not None:
            f = _raw(nxt[brk_idx])
            if _is_tracer(f):
                # the break became data-dependent under trace: restart the
                # whole loop as a while_loop (the partial trace is dead code
                # that XLA eliminates)
                return traced()
            cur = nxt
            if bool(f):
                break
        else:
            cur = nxt
    return cur


def convert_iter_for(iterable, body_fn, carry, item_idx=None, brk_idx=None):
    """Runtime of a transformed `for` over a non-range iterable. Tensors
    iterate their leading axis (traced: static trip count + dynamic
    indexing); plain python iterables keep python semantics."""
    r = _raw(iterable)
    is_arr = _is_tracer(r) or isinstance(r, (jax.Array, jnp.ndarray))
    carry0 = tuple(carry)
    if is_arr:
        n = int(r.shape[0])

        def traced():
            if n == 0:
                return carry0
            return _traced_loop(n, lambda k: r[k], r[0], body_fn, carry0,
                                item_idx, brk_idx)

        if _is_tracer(r):
            return traced()
        items = [Tensor(r[k], stop_gradient=True) for k in range(n)]
    else:
        items = list(iterable)
    cur = carry0
    for item in items:
        nxt = body_fn(item, *cur)
        if brk_idx is not None:
            f = _raw(nxt[brk_idx])
            if _is_tracer(f):
                if is_arr:
                    return traced()
                raise Dy2StaticError(
                    "a data-dependent `break` requires iterating a Tensor "
                    "or range(...)")
            cur = nxt
            if bool(f):
                break
        else:
            cur = nxt
    return cur


# ---------------------------------------------------------------------------
# AST transformation
# ---------------------------------------------------------------------------

class _AssignedNames(ast.NodeVisitor):
    """Names assigned (Store context) in a statement list, not descending
    into nested function/class scopes."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _has_flow_escape(stmts):
    """True if the statement list contains top-scope return/break/continue
    (not inside a nested function or a nested loop for break/continue)."""
    if _has_return(stmts):
        return True
    return any(_find_bc(stmts))


def _has_return(stmts):
    """`return` anywhere in the region (descends into nested loops, not
    into nested function scopes)."""
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, node):
            self.found = True

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass
    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _find_bc(stmts):
    """(has_break, has_continue) at THIS loop's scope — a nested loop's
    BODY owns its break/continue, but its `else` clause belongs to us;
    nested functions own everything."""
    class V(ast.NodeVisitor):
        brk = False
        cont = False

        def visit_Break(self, node):
            self.brk = True

        def visit_Continue(self, node):
            self.cont = True

        def visit_For(self, node):
            for s in node.orelse:
                self.visit(s)

        visit_AsyncFor = visit_For
        visit_While = visit_For

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef
    v = V()
    for s in stmts:
        v.visit(s)
    return v.brk, v.cont


def _assign_const(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=ast.Constant(value=value))


def _lower_escapes(stmts, brk, cont):
    """Replace this loop's break/continue with flag assignments, guarding
    every statement that follows a possible flag-set with
    `if _d2s_keep_going(flags): ...` (reference analog:
    break_continue_transformer.py). Returns None when the region holds a
    break/continue inside a construct we don't lower (try/with)."""
    out = []
    for i, s in enumerate(stmts):
        may = False
        if isinstance(s, ast.Break):
            out.append(_assign_const(brk, True))
            may = True
        elif isinstance(s, ast.Continue):
            out.append(_assign_const(cont, True))
            may = True
        elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
            if any(_find_bc([s])):
                return None        # break/continue in the inner loop's else
            out.append(s)          # inner loop owns its body's break/continue
        elif isinstance(s, ast.If) and any(_find_bc([s])):
            b = _lower_escapes(s.body, brk, cont)
            o = _lower_escapes(s.orelse, brk, cont)
            if b is None or o is None:
                return None
            out.append(ast.If(test=s.test, body=b or [ast.Pass()],
                              orelse=o))
            may = True
        elif any(_find_bc([s])):
            return None            # break/continue under try/with etc.
        else:
            out.append(s)
        if may:
            rest = stmts[i + 1:]
            if rest:
                lowered = _lower_escapes(rest, brk, cont)
                if lowered is None:
                    return None
                flags = [f for f in (brk, cont) if f is not None]
                out.append(ast.If(
                    test=ast.Call(
                        func=ast.Name(id="_d2s_keep_going", ctx=ast.Load()),
                        args=[ast.Name(id=f, ctx=ast.Load())
                              for f in flags],
                        keywords=[]),
                    body=lowered, orelse=[]))
            return out
    return out


def _names_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


class _Undefined:
    """Sentinel for locals not yet bound when a transformed region starts
    (reference analog: dygraph_to_static UndefinedVar)."""

    def __repr__(self):
        return "<dy2static undefined>"


UNDEFINED = _Undefined()


def _undef_guard(name):
    """`try: name\nexcept NameError|UnboundLocalError: name = UNDEFINED`"""
    return ast.Try(
        body=[ast.Expr(value=ast.Name(id=name, ctx=ast.Load()))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(elts=[ast.Name(id="NameError", ctx=ast.Load()),
                                 ast.Name(id="UnboundLocalError",
                                          ctx=ast.Load())],
                           ctx=ast.Load()),
            name=None,
            body=[ast.Assign(
                targets=[ast.Name(id=name, ctx=ast.Store())],
                value=ast.Name(id="_d2s_UNDEFINED", ctx=ast.Load()))])],
        orelse=[], finalbody=[])


class _CtrlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._k = 0

    def _fresh(self, kind):
        self._k += 1
        return f"_d2s_{kind}_{self._k}"

    def _make_fn(self, name, arg_names, body, ret_names):
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in arg_names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        ret = ast.Return(value=_names_tuple(ret_names, ast.Load))
        return ast.FunctionDef(name=name, args=args, body=body + [ret],
                               decorator_list=[], returns=None,
                               type_params=[])

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            return node
        names = sorted(_assigned(node.body) | _assigned(node.orelse))
        if not names:
            return node
        tname = self._fresh("true")
        fname = self._fresh("false")
        tfn = self._make_fn(tname, names, node.body, names)
        ffn = self._make_fn(fname, names,
                            node.orelse if node.orelse else [ast.Pass()],
                            names)
        call = ast.Call(
            func=ast.Name(id="_d2s_convert_ifelse", ctx=ast.Load()),
            args=[node.test, ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  _names_tuple(names, ast.Load)], keywords=[])
        assign = ast.Assign(targets=[_names_tuple(names, ast.Store)],
                            value=call)
        return [_undef_guard(n) for n in names] + [tfn, ffn, assign]

    def _visit_stmts(self, stmts):
        out = []
        for s in stmts:
            r = self.visit(s)
            out.extend(r if isinstance(r, list) else [r])
        return out

    def _prep_loop_body(self, body):
        """Lower break/continue to flags. Returns (body', brk, cont) or
        None when the loop must stay untransformed."""
        hb, hc = _find_bc(body)
        brk = self._fresh("brk") if hb else None
        cont = self._fresh("cont") if hc else None
        if hb or hc:
            body = _lower_escapes(body, brk, cont)
            if body is None:
                return None
        if cont is not None:
            body = [_assign_const(cont, False)] + body
        return body, brk, cont

    def visit_While(self, node):
        if node.orelse or _has_return(node.body):
            self.generic_visit(node)
            return node
        prep = self._prep_loop_body(node.body)
        if prep is None:
            self.generic_visit(node)
            return node
        body, brk, cont = prep
        new_body = self._visit_stmts(body)
        node.test = self.visit(node.test)
        names = sorted(_assigned(new_body))
        if not names:
            node.body = new_body
            return node
        cname = self._fresh("cond")
        bname = self._fresh("body")
        cargs = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        if brk is not None:
            # (not _brk) and (test), short-circuit-safe and trace-safe
            test = ast.Call(
                func=ast.Name(id="_d2s_and_not", ctx=ast.Load()),
                args=[ast.Name(id=brk, ctx=ast.Load()),
                      ast.Lambda(
                          args=ast.arguments(
                              posonlyargs=[], args=[], vararg=None,
                              kwonlyargs=[], kw_defaults=[], kwarg=None,
                              defaults=[]),
                          body=node.test)],
                keywords=[])
        else:
            test = node.test
        cfn = ast.FunctionDef(
            name=cname, args=cargs, body=[ast.Return(value=test)],
            decorator_list=[], returns=None, type_params=[])
        bfn = self._make_fn(bname, names, new_body, names)
        call = ast.Call(
            func=ast.Name(id="_d2s_convert_while", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  _names_tuple(names, ast.Load)], keywords=[])
        assign = ast.Assign(targets=[_names_tuple(names, ast.Store)],
                            value=call)
        guards = [_undef_guard(n) for n in names if n not in (brk, cont)]
        inits = [_assign_const(f, False) for f in (brk, cont)
                 if f is not None]
        return guards + inits + [cfn, bfn, assign]

    def visit_For(self, node):
        if node.orelse or _has_return(node.body):
            self.generic_visit(node)
            return node
        if isinstance(node.target, ast.Name):
            tnames = [node.target.id]
        elif isinstance(node.target, ast.Tuple) and all(
                isinstance(e, ast.Name) for e in node.target.elts):
            tnames = [e.id for e in node.target.elts]
        else:
            self.generic_visit(node)
            return node
        prep = self._prep_loop_body(node.body)
        if prep is None:
            self.generic_visit(node)
            return node
        body, brk, cont = prep
        item = self._fresh("item")
        tassign = ast.Assign(targets=[node.target],
                             value=ast.Name(id=item, ctx=ast.Load()))
        # continue-flag reset must precede the target assign; _prep put it
        # at index 0 when present
        if cont is not None:
            body = [body[0], tassign] + body[1:]
        else:
            body = [tassign] + body
        new_body = self._visit_stmts(body)
        node.iter = self.visit(node.iter)
        names = sorted(_assigned(new_body))
        item_idx = names.index(tnames[0]) if len(tnames) == 1 else None
        brk_idx = names.index(brk) if brk is not None else None
        bname = self._fresh("body")
        bfn = self._make_fn(bname, [item] + names, new_body, names)
        if isinstance(node.iter, ast.Call) and \
                isinstance(node.iter.func, ast.Name) and \
                node.iter.func.id == "range" and not node.iter.keywords and \
                not any(isinstance(a, ast.Starred) for a in node.iter.args):
            fn_name = "_d2s_convert_range_for"
            iter_arg = ast.Tuple(elts=list(node.iter.args), ctx=ast.Load())
        else:
            fn_name = "_d2s_convert_iter_for"
            iter_arg = node.iter
        call = ast.Call(
            func=ast.Name(id=fn_name, ctx=ast.Load()),
            args=[iter_arg, ast.Name(id=bname, ctx=ast.Load()),
                  _names_tuple(names, ast.Load),
                  ast.Constant(value=item_idx),
                  ast.Constant(value=brk_idx)], keywords=[])
        assign = ast.Assign(targets=[_names_tuple(names, ast.Store)],
                            value=call)
        guards = [_undef_guard(n) for n in names if n not in (brk, cont)]
        inits = [_assign_const(f, False) for f in (brk, cont)
                 if f is not None]
        return guards + inits + [bfn, assign]


def ast_transform(func):
    """Rewrite `func`'s data-dependent if/while into convert_ifelse /
    convert_while calls; returns the transformed function, or None when the
    function can't be transformed (no source, closures)."""
    raw = getattr(func, "__func__", func)
    if getattr(raw, "__closure__", None):
        return None
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return None
    fndef = tree.body[0]
    if not isinstance(fndef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fndef.decorator_list = []
    new_tree = _CtrlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    ns = dict(raw.__globals__)
    ns["_d2s_convert_ifelse"] = convert_ifelse
    ns["_d2s_convert_while"] = convert_while
    ns["_d2s_convert_range_for"] = convert_range_for
    ns["_d2s_convert_iter_for"] = convert_iter_for
    ns["_d2s_and_not"] = and_not_flag
    ns["_d2s_keep_going"] = keep_going
    ns["_d2s_UNDEFINED"] = UNDEFINED
    try:
        code = compile(new_tree, filename=f"<dy2static:{raw.__name__}>",
                       mode="exec")
    except (SyntaxError, ValueError):
        # a construct the transformer mishandled — fall back to untransformed
        return None
    exec(code, ns)
    new_fn = ns[fndef.name]
    new_fn.__dy2static__ = True
    return new_fn
