"""TrainStep: a fully-fused jitted training step.

Reference analog: the whole dygraph hot loop (forward ad_funcs + RunBackward +
optimizer ops) collapsed into one XLA executable — the TPU-first answer to the
reference's per-op C++ dispatch war (phi README §1.2).

    step = TrainStep(model, loss_fn, optimizer)
    loss = step(batch_x, batch_y)          # one compiled fwd+bwd+update

Parameters and optimizer slots live as donated pytrees across steps; the
model's wrapper tensors are refreshed after each call so eager inspection
(state_dict, p.numpy()) still works.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework import random as _random
from ..framework.autograd import set_grad_enabled

__all__ = ["TrainStep", "bake_decay_flags", "donation_argnums"]


def bake_decay_flags(opt, params):
    """Prime the optimizer's per-param weight-decay flag list for a traced
    update: AdamW/Lamb/Lars `_single_update` implementations consume
    `_current_decay_flags` in parameter order at trace time, so any builder
    that jit-compiles `_single_update` over a parameter list (TrainStep and
    the eager auto-TrainStep in ops/step_fusion.py) must bake them first."""
    if hasattr(opt, "_decay_skip"):
        opt._current_decay_flags = [p.name not in opt._decay_skip
                                    for p in params]
    elif hasattr(opt, "_decay_flags"):
        opt._current_decay_flags = [opt._decay_flags.get(p.name, True)
                                    for p in params]


def donation_argnums(donate_params, params_pos, accs_pos):
    """Donation spec shared by TrainStep and the eager auto-TrainStep:
    optimizer-slot (accumulator) buffers are always donated — exactly what
    the eager optimizer's own fused update does — while parameter buffers
    are only donated on request, because user-held aliases of `p._value`
    (detach() shares storage) would be invalidated."""
    return (params_pos, accs_pos) if donate_params else (accs_pos,)


class TrainStep:
    def __init__(self, model, loss_fn, optimizer, donate=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._jitted = None
        self._params = None
        self._acc_names = None
        self._donate = donate

    def _build(self, example_args):
        model = self.model
        loss_fn = self.loss_fn
        opt = self.optimizer
        params = [p for p in model.parameters() if not p.stop_gradient]
        buffers = [b for _, b in model.named_buffers()]
        self._params = params
        self._buffers = buffers
        opt._create_accumulators(params)
        acc_names = sorted(opt._accumulators.keys())
        self._acc_names = acc_names

        def pure_loss(pvals, bvals, args, key):
            saved_p = [p._value for p in params]
            saved_b = [b._value for b in buffers]
            saved_flags = [p.stop_gradient for p in params]
            try:
                for p, v in zip(params, pvals):
                    p._value = v
                    p.stop_gradient = True
                for b, v in zip(buffers, bvals):
                    b._value = v
                targs = [Tensor(a, stop_gradient=True) for a in args]
                with _random.tracing_key_scope(key):
                    with set_grad_enabled(False):
                        out = model(*targs[:-1]) if loss_fn is not None \
                            else model(*targs)
                        loss = loss_fn(out, targs[-1]) if loss_fn is not None \
                            else out
                new_b = [b._value for b in buffers]
                return loss._value, new_b
            finally:
                for p, v, sg in zip(params, saved_p, saved_flags):
                    p._value = v
                    p.stop_gradient = sg
                for b, v in zip(buffers, saved_b):
                    b._value = v

        # bake per-param decay flags for AdamW/Lamb before tracing
        bake_decay_flags(opt, params)

        def step(pvals, accs, bvals, args, lr, step_count, key):
            (loss, new_b), grads = jax.value_and_grad(
                pure_loss, has_aux=True)(pvals, bvals, args, key)
            new_p, new_accs = [], []
            for pv, gv, ac in zip(pvals, grads, accs):
                acc_dict = dict(zip(acc_names, ac))
                np_, na_ = opt._single_update(pv, gv, acc_dict, lr, step_count)
                new_p.append(np_)
                # .get: f32 params have no master_weight entry under
                # multi_precision
                new_accs.append([na_.get(n) for n in acc_names])
            return loss, new_p, new_accs, new_b

        # donate accumulators by default; donating params would invalidate
        # user-held aliases of p._value (detach() shares storage). Pass
        # donate="all" for maximum-memory-efficiency training loops that
        # never alias parameters.
        if self._donate == "all":
            donate = donation_argnums(True, 0, 1) + (2,)
        elif self._donate:
            donate = donation_argnums(False, 0, 1)
        else:
            donate = ()
        self._jitted = jax.jit(step, donate_argnums=donate)

    def __call__(self, *args):
        arg_vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                    for a in args]
        if self._jitted is None:
            self._build(arg_vals)
        params = self._params
        opt = self.optimizer
        acc_names = self._acc_names
        opt._create_accumulators(params)
        if not hasattr(opt, "_step_count"):
            opt._step_count = 0
        opt._step_count += 1

        pvals = [p._value for p in params]
        accs = [[opt._accumulators[n].get(p.name) for n in acc_names]
                for p in params]
        bvals = [b._value for b in self._buffers]
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        step_count = jnp.asarray(opt._step_count, jnp.int32)
        key = _random.get_rng_key()

        loss, new_p, new_accs, new_b = self._jitted(
            pvals, accs, bvals, arg_vals, lr, step_count, key)
        from ..framework.flags import _FLAGS
        if _FLAGS.get("FLAGS_check_nan_inf") and \
                not bool(jnp.isfinite(loss)):
            # keep the (non-donated) pre-step parameters so an eager re-run
            # can locate the bad op; the donated accumulator buffers are
            # gone, so their new values must land regardless
            for p, ac in zip(params, new_accs):
                for n, v in zip(acc_names, ac):
                    if v is not None:
                        opt._accumulators[n][p.name] = v
            raise FloatingPointError(
                "TrainStep produced a non-finite loss "
                "(FLAGS_check_nan_inf); parameters were NOT updated "
                "(optimizer accumulators were) — re-run the step eagerly "
                "to locate the offending op")
        for p, v in zip(params, new_p):
            p._value = v
        for p, ac in zip(params, new_accs):
            for n, v in zip(acc_names, ac):
                if v is not None:
                    opt._accumulators[n][p.name] = v
        for b, v in zip(self._buffers, new_b):
            b._value = v
        # goodput accountant (profiler/goodput.py): the explicit fused
        # TrainStep never crosses Optimizer.step, so the boundary is here
        from ..profiler import goodput as _goodput
        _goodput.on_step(opt)
        return Tensor(loss)
