from .api import (  # noqa: F401
    to_static, not_to_static, ignore_module, TracedLayer, TranslatedLayer,
    save, load, InputSpec)
from .train_step import TrainStep  # noqa: F401


class ProgramTranslator:
    """dy2static controller singleton (reference:
    dygraph_to_static/program_translator.py ProgramTranslator): a global
    enable/disable switch the @to_static machinery consults."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.enable_to_static = True
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static):
        self.enable_to_static = bool(enable_to_static)


_CODE_LEVEL = 0
_VERBOSITY = 0


def set_code_level(level=100, also_to_stdout=False):
    """Reference: dygraph_to_static/logging_utils.py set_code_level —
    controls transformed-code dumping."""
    global _CODE_LEVEL
    _CODE_LEVEL = level


def set_verbosity(level=0, also_to_stdout=False):
    """Reference: dygraph_to_static/logging_utils.py set_verbosity."""
    global _VERBOSITY
    _VERBOSITY = level
