from .api import to_static, not_to_static, ignore_module, TracedLayer, save, load  # noqa: F401
from .train_step import TrainStep  # noqa: F401
