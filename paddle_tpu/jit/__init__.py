from .api import (  # noqa: F401
    to_static, not_to_static, ignore_module, TracedLayer, TranslatedLayer,
    save, load, InputSpec)
from .train_step import TrainStep  # noqa: F401
