"""@to_static: dygraph-to-static capture.

Reference analog: python/paddle/fluid/dygraph/jit.py:204 (declarative /
to_static) + dygraph_to_static/program_translator.py. The reference rewrites
Python AST into a ProgramDesc; TPU-first we trace the callable into a jaxpr and
run it as ONE compiled XLA executable (SURVEY.md §7 row 4: ProgramDesc +
InterpreterCore ≙ jaxpr + XLA runtime).

Autograd composition: when any input/parameter requires grad, the whole traced
function is dispatched as a single op through the eager tape (its VJP is the
XLA-compiled backward), so `loss.backward()` works unchanged but pays one
kernel launch instead of per-op dispatch.
"""
from __future__ import annotations

import functools
import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, Parameter
from ..framework import random as _random
from ..framework.autograd import is_grad_enabled
from ..nn.layer_base import Layer
from ..ops.dispatch import call_op_multi

__all__ = ["to_static", "not_to_static", "ignore_module", "TracedLayer",
           "TranslatedLayer", "save", "load", "InputSpec"]

_ignored_modules = set()


class InputSpec:
    """Reference analog: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _collect_state(obj):
    """All (tensor, requires_grad) pairs the callable closes over."""
    if isinstance(obj, Layer):
        params = list(dict.fromkeys(
            p for _, p in obj.named_parameters()))
        buffers = [b for _, b in obj.named_buffers()]
        return params, buffers
    owner = getattr(obj, "__self__", None)
    if isinstance(owner, Layer):
        return _collect_state(owner)
    return [], []


class StaticFunction:
    def __init__(self, function, input_spec=None, build_strategy=None,
                 full_graph=True):
        self._function = function
        self._input_spec = input_spec
        self._layer = function if isinstance(function, Layer) else None
        functools.update_wrapper(
            self, function.forward if self._layer else function)
        self._lock = threading.Lock()
        self._jitted = {}
        self._last_out_treedef = None

    @property
    def forward_callable(self):
        if getattr(self, "_transformed_fwd", None) is not None:
            return self._transformed_fwd
        return self._layer.forward if self._layer is not None else self._function

    def _apply_dy2static(self):
        """Retry hook: rewrite data-dependent if/while via the dy2static AST
        transformer (reference analog: program_translator.py falling back to
        dygraph_to_static conversion). Returns True when a transform was
        installed."""
        if getattr(self, "_transformed_fwd", None) is not None:
            return False
        from .dy2static import ast_transform
        import types as _types
        base = self._layer.forward if self._layer is not None \
            else self._function
        new_fn = ast_transform(base)
        if new_fn is None:
            return False
        if self._layer is not None:
            new_fn = _types.MethodType(new_fn, self._layer)
        self._transformed_fwd = new_fn
        self._jitted.clear()
        return True

    def _make_pure(self, params, buffers, tensor_args_spec, static_args):
        fwd = self.forward_callable
        n_params = len(params)
        n_buffers = len(buffers)

        def pure(values, key):
            pvals = values[:n_params]
            bvals = values[n_params:n_params + n_buffers]
            avals = values[n_params + n_buffers:]
            saved_p = [p._value for p in params]
            saved_b = [b._value for b in buffers]
            saved_flags = [p.stop_gradient for p in params]
            arg_tensors = []
            try:
                for p, v in zip(params, pvals):
                    p._value = v
                    # tape must not record inside the trace; jax handles AD
                    p.stop_gradient = True
                for b, v in zip(buffers, bvals):
                    b._value = v
                args = []
                ai = 0
                for spec in tensor_args_spec:
                    if spec == "__tensor__":
                        t = Tensor(avals[ai], stop_gradient=True)
                        ai += 1
                        args.append(t)
                    else:
                        args.append(spec)
                with _random.tracing_key_scope(key):
                    from ..framework.autograd import set_grad_enabled
                    with set_grad_enabled(False):
                        out = fwd(*args, **static_args)
                flat, treedef = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, Tensor))
                out_vals = tuple(f._value if isinstance(f, Tensor)
                                 else jnp.asarray(f) for f in flat)
                self._last_out_treedef = treedef
                new_buffer_vals = tuple(b._value for b in buffers)
                return out_vals + new_buffer_vals
            finally:
                for p, v, sg in zip(params, saved_p, saved_flags):
                    p._value = v
                    p.stop_gradient = sg
                for b, v in zip(buffers, saved_b):
                    b._value = v
        return pure

    def __call__(self, *args, **kwargs):
        from . import ProgramTranslator
        if not ProgramTranslator().enable_to_static:
            # reference: ProgramTranslator.enable(False) runs dygraph
            fwd = self._layer.forward if self._layer is not None \
                else self._function
            return fwd(*args, **kwargs)
        params, buffers = _collect_state(
            self._layer if self._layer is not None else self._function)
        tensor_args = []
        spec = []
        for a in args:
            if isinstance(a, Tensor):
                spec.append("__tensor__")
                tensor_args.append(a)
            elif isinstance(a, (np.ndarray, jnp.ndarray)) and not np.isscalar(a):
                t = Tensor(a)
                spec.append("__tensor__")
                tensor_args.append(t)
            else:
                spec.append(a)

        training = self._layer.training if self._layer is not None else True
        cache_key = (
            tuple((tuple(t.shape), t._value.dtype) for t in tensor_args),
            tuple(sorted(kwargs.items())) if all(
                isinstance(v, (int, float, str, bool, type(None)))
                for v in kwargs.values()) else None,
            training,
        )
        all_inputs = params + buffers + tensor_args
        values = [t._value for t in all_inputs]
        key = _random.get_rng_key()

        def build():
            with self._lock:
                entry = self._jitted.get(cache_key)
                if entry is None:
                    pure = self._make_pure(params, buffers, spec, kwargs)
                    entry = (pure, jax.jit(pure))
                    self._jitted[cache_key] = entry
            return entry

        pure, jitted = build()

        requires_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in all_inputs)
        n_out_extra = len(buffers)
        # data-dependent python control flow fails the FIRST trace of a new
        # signature; rewrite via the dy2static AST pass and retry once (no
        # extra tracing on the happy path)
        from jax.errors import JAXTypeError
        if not requires_grad:
            try:
                out_vals = jitted(values, key)
            except JAXTypeError:
                if not self._apply_dy2static():
                    raise
                pure, jitted = build()
                out_vals = jitted(values, key)
        else:
            # one GradNode for the whole compiled function
            diff_idx = [i for i, t in enumerate(all_inputs)
                        if not t.stop_gradient and
                        jnp.issubdtype(t._value.dtype, jnp.inexact)]

            def make_fn(jitted_):
                def fn(*diff_vals):
                    full = list(values)
                    for i, v in zip(diff_idx, diff_vals):
                        full[i] = v
                    return jitted_(full, key)
                return fn

            try:
                out_vals, vjp_fn = jax.vjp(
                    make_fn(jitted), *(values[i] for i in diff_idx))
            except JAXTypeError:
                if not self._apply_dy2static():
                    raise
                pure, jitted = build()
                out_vals, vjp_fn = jax.vjp(
                    make_fn(jitted), *(values[i] for i in diff_idx))

            def wrapped_vjp(gs, _vjp=vjp_fn, _idx=diff_idx,
                            _n=len(all_inputs)):
                if not isinstance(gs, tuple):
                    # engine passes a bare cotangent for single-output fns;
                    # jax.vjp of a tuple-returning fn wants a tuple
                    gs = (gs,)
                partial = _vjp(gs)
                full = [None] * _n
                for i, pg in zip(_idx, partial):
                    full[i] = pg
                return tuple(full)

            from ..framework.autograd import GradNode
            from ..ops.dispatch import _make_edges
            node = GradNode("to_static", wrapped_vjp,
                            _make_edges(all_inputs),
                            tuple((v.shape, v.dtype) for v in out_vals))

        # split model outputs from updated buffer state
        n_model_out = len(out_vals) - n_out_extra
        model_out_vals = out_vals[:n_model_out]
        new_buf_vals = out_vals[n_model_out:]
        for b, v in zip(buffers, new_buf_vals):
            b._value = v

        outs = []
        for j, v in enumerate(model_out_vals):
            t = Tensor(v, stop_gradient=not requires_grad)
            if requires_grad:
                t._grad_node = node
                t._out_index = j
                t.stop_gradient = False
            outs.append(t)
        if not hasattr(self, "_treedefs"):
            self._treedefs = {}
        if cache_key not in self._treedefs and \
                self._last_out_treedef is not None:
            self._treedefs[cache_key] = self._last_out_treedef
        treedef = self._treedefs.get(cache_key)
        if treedef is not None:
            # rebuild original structure; non-tensor leaves became tensors
            try:
                rebuilt = jax.tree_util.tree_unflatten(treedef, outs)
                return rebuilt
            except Exception:
                pass
        return outs[0] if len(outs) == 1 else tuple(outs)

    # -- program-artifact API ------------------------------------------------
    def concrete_program(self, *args):
        """Return the jaxpr for given example args (ProgramDesc analog)."""
        params, buffers = _collect_state(
            self._layer if self._layer is not None else self._function)
        tensor_args = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
        pure = self._make_pure(params, buffers,
                               ["__tensor__"] * len(tensor_args), {})
        values = [t._value for t in params + buffers + tensor_args]
        key = jax.random.key(0)
        return jax.make_jaxpr(pure)(values, key)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator/wrapper. Accepts a Layer or a function (paddle.jit.to_static)."""
    def wrap(f):
        if type(f) is StaticFunction:
            return f
        if f in _ignored_modules if isinstance(f, type) else False:
            return f
        return StaticFunction(f, input_spec=input_spec,
                              build_strategy=build_strategy)
    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(func):
    func._not_to_static = True
    return func


def ignore_module(modules):
    _ignored_modules.update(modules)


class TracedLayer:
    """Reference analog: fluid/dygraph/jit.py TracedLayer."""

    def __init__(self, static_fn):
        self._fn = static_fn

    @staticmethod
    def trace(layer, inputs):
        sf = to_static(layer)
        outs = sf(*inputs)
        return outs, TracedLayer(sf)

    def __call__(self, *args):
        return self._fn(*args)


def save(layer, path, input_spec=None, **configs):
    """jit.save: persist weights + exported StableHLO for the forward.

    Reference analog: paddle.jit.save (TranslatedLayer protocol). The artifact
    is a pickle with the state dict; where input_spec is given, an
    `jax.export`-serialized compiled forward is attached for
    deployment parity with save_inference_model.
    """
    from ..framework.io import save as fsave
    payload = {"format": "paddle_tpu.jit", "version": 1}
    if isinstance(layer, StaticFunction):
        model = layer._layer
    else:
        model = layer
    if isinstance(model, Layer):
        payload["state_dict"] = dict(model.state_dict())
        payload["class_name"] = type(model).__name__
    if input_spec:
        try:
            from jax import export as jexport
            sf = layer if isinstance(layer, StaticFunction) else to_static(layer)
            params, buffers = _collect_state(model)
            # the exact state values the export closes over, in call order —
            # state_dict() can't reconstruct this (non-persistable buffers
            # are part of the signature but not the state dict)
            payload["export_state"] = [np.asarray(t._value)
                                       for t in params + buffers]
            # map each export_state slot to its state_dict key so a
            # program-only artifact (static.serialize_program strips the
            # values) can be re-armed from deserialize_persistables
            by_id = {id(v): k for k, v in payload.get("state_dict",
                                                      {}).items()}
            payload["export_state_keys"] = [by_id.get(id(t))
                                            for t in params + buffers]
            # the exported pure fn returns model outputs + updated buffers;
            # load needs the split point
            payload["n_buffer_outputs"] = len(buffers)
            specs = [jax.ShapeDtypeStruct(
                tuple(s.shape),
                np.dtype(getattr(s, "dtype", "float32") if not hasattr(
                    s.dtype, "np_dtype") else s.dtype.np_dtype))
                for s in input_spec]
            pure = sf._make_pure(params, buffers,
                                 ["__tensor__"] * len(specs), {})
            values_spec = [jax.ShapeDtypeStruct(v._value.shape, v._value.dtype)
                          for v in params + buffers] + list(specs)
            from ..framework.jax_compat import export_key_form
            key_form = export_key_form()
            key_spec = jax.ShapeDtypeStruct((), jax.random.key(0).dtype) \
                if key_form == "typed" \
                else jax.ShapeDtypeStruct((2,), jnp.uint32)
            exported = jexport.export(jax.jit(pure))(values_spec, key_spec)
            payload["stablehlo"] = exported.serialize()
            payload["export_key_form"] = key_form
        except Exception as e:  # serialization is best-effort
            payload["stablehlo_error"] = repr(e)
    fsave(payload, path if path.endswith(".pdmodel") or "." in path.split("/")[-1]
          else path + ".pdmodel")


class TranslatedLayer:
    """Callable artifact returned by jit.load (reference analog:
    fluid/dygraph/io.py TranslatedLayer): runs the jax.export-serialized
    forward with the saved weights; falls back to weights-only access when
    no compiled forward was attached."""

    def __init__(self, payload):
        self._payload = payload
        self._state_dict = payload.get("state_dict", {})
        self._exported = None
        blob = payload.get("stablehlo")
        if blob is not None:
            from jax import export as jexport
            self._exported = jexport.deserialize(blob)
        export_state = payload.get("export_state")
        if export_state is not None:
            self._param_values = [jnp.asarray(v) for v in export_state]
        else:  # older artifacts: persistable state only
            self._param_values = [t._value
                                  for t in self._state_dict.values()]

    @property
    def has_forward(self):
        return self._exported is not None

    def state_dict(self):
        return dict(self._state_dict)

    def set_state(self, state):
        """Arm a program-only artifact (static.serialize_program strips
        weights) with persistables from deserialize_persistables: values
        map into export-state slots by their state_dict keys."""
        keys = self._payload.get("export_state_keys")
        if not keys:
            raise RuntimeError(
                "this artifact predates export_state_keys; re-save it")
        aux = self._payload.get("export_state_aux") or {}
        vals = []
        for i, k in enumerate(keys):
            if k is None:
                # non-persistable buffer: not a persistable by definition —
                # its value rides with the program (export_state_aux)
                if i not in aux:
                    raise KeyError(
                        f"program artifact lacks the non-persistable "
                        f"buffer for export slot {i}")
                vals.append(jnp.asarray(aux[i]))
                continue
            if k not in state:
                raise KeyError(f"persistables missing state slot {k!r}")
            v = state[k]
            vals.append(v._value if isinstance(v, Tensor) else
                        jnp.asarray(v))
        self._param_values = vals

    def __call__(self, *args):
        if self._exported is None:
            err = self._payload.get("stablehlo_error")
            raise RuntimeError(
                "this artifact was saved without input_spec so no compiled "
                "forward is attached" + (f" (export error: {err})" if err
                                         else ""))
        vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        # the key form is an artifact property, not an env property: call
        # with whatever the export was traced with (see jax_compat)
        key = jax.random.key(0) \
            if self._payload.get("export_key_form", "typed") == "typed" \
            else jax.random.PRNGKey(0)
        out = self._exported.call(self._param_values + vals, key)
        if isinstance(out, (list, tuple)):
            n_buf = self._payload.get("n_buffer_outputs", 0)
            model_out = list(out[:len(out) - n_buf]) if n_buf else list(out)
            outs = [Tensor(o, stop_gradient=True) for o in model_out]
            return outs[0] if len(outs) == 1 else outs
        return Tensor(out, stop_gradient=True)

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only; rebuild the "
                           "Layer and set_state_dict to fine-tune")


def load(path, **configs):
    from ..framework.io import load as fload
    try:
        payload = fload(path)
    except FileNotFoundError:
        payload = fload(path + ".pdmodel")
    if isinstance(payload, dict) and payload.get("format") == \
            "paddle_tpu.jit":
        return TranslatedLayer(payload)
    return payload
