"""FLOPs counter (`paddle.flops`).

Reference analog: python/paddle/hapi/dynamic_flops.py — per-layer-type FLOP
rules evaluated via forward hooks on a dummy run. Counts multiply-adds as
the reference does (one MAC = 1 FLOP here, matching its convention).
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor

__all__ = ["flops"]


def _numel(t):
    if isinstance(t, Tensor):
        return int(np.prod(t.shape)) if t.shape else 1
    return 0


def _first(out):
    if isinstance(out, (list, tuple)):
        for o in out:
            if isinstance(o, Tensor):
                return o
    return out


def _count_linear(layer, inp, out):
    out = _first(out)
    in_f = int(layer.weight.shape[0])
    return _numel(out) * in_f


def _count_conv(layer, inp, out):
    out = _first(out)
    w = layer.weight
    kernel_ops = int(np.prod(w.shape[1:]))  # C_in/groups * kh * kw
    return _numel(out) * kernel_ops


def _count_norm(layer, inp, out):
    return 2 * _numel(_first(out))


def _count_act(layer, inp, out):
    return _numel(_first(out))


def _count_pool(layer, inp, out):
    return _numel(_first(out))


def _count_embedding(layer, inp, out):
    return 0


def _rules():
    from .. import nn
    rules = {}

    def add(names, fn):
        for n in names:
            cls = getattr(nn, n, None)
            if cls is not None:
                rules[cls] = fn

    add(["Linear"], _count_linear)
    add(["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
         "Conv3DTranspose"], _count_conv)
    add(["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
         "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
         "InstanceNorm3D", "SyncBatchNorm"], _count_norm)
    add(["ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax", "LeakyReLU",
         "Hardswish", "Hardsigmoid", "SiLU", "PReLU", "ELU"], _count_act)
    add(["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
         "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
         "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
         "AdaptiveMaxPool3D"], _count_pool)
    add(["Embedding"], _count_embedding)
    return rules


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """Count forward FLOPs of `net` on a dummy input of `input_size`.

    custom_ops: {LayerClass: fn(layer, inputs, output) -> int} overrides.
    Returns the total as an int.
    """
    import jax.numpy as jnp

    if inputs is None:
        if input_size is None:
            raise ValueError("flops needs input_size or inputs")
        shape = tuple(1 if (d is None or d == -1) else int(d)
                      for d in input_size)
        inputs = [Tensor(jnp.ones(shape, jnp.float32), stop_gradient=True)]
    elif not isinstance(inputs, (list, tuple)):
        inputs = [inputs]

    rules = _rules()
    if custom_ops:
        rules.update(custom_ops)

    counts = []
    hooks = []

    def make_hook(fn, name):
        def hook(layer, inp, out):
            counts.append((name, type(layer).__name__, int(fn(layer, inp, out))))
        return hook

    layers = [("", net)] if not list(net.children()) else \
        list(net.named_sublayers())
    for name, sub in layers:
        if list(sub.children()):
            continue
        fn = rules.get(type(sub))
        if fn is None:  # walk the MRO so subclasses inherit their rule
            for cls, f in rules.items():
                if isinstance(sub, cls):
                    fn = f
                    break
        if fn is not None:
            hooks.append(sub.register_forward_post_hook(make_hook(fn, name)))

    was_training = net.training
    net.eval()
    try:
        net(*inputs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(c for _, _, c in counts)
    if print_detail:
        for name, typ, c in counts:
            print(f"{name:<40} {typ:<20} {c:>16,}")
    print(f"Total Flops: {total}")
    return total
