"""Terminal progress bar. Reference analog: python/paddle/hapi/progressbar.py."""
from __future__ import annotations

import sys
import time

__all__ = ["ProgressBar"]


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, start=True,
                 file=sys.stdout):
        self._num = num
        self._width = width if num is not None else 0
        self._verbose = verbose
        self.file = file
        self._values = {}
        self._last_update = 0
        if start:
            self._start = time.time()

    def start(self):
        self.file.flush()
        self._start = time.time()

    def update(self, current_num, values=None):
        now = time.time()
        if values:
            self._values.update(values)
        if self._verbose == 0:
            return
        metrics = " - ".join(
            f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
            for k, v in self._values.items())
        if self._num is not None:
            frac = min(float(current_num) / self._num, 1.0)
            filled = int(self._width * frac)
            bar = "=" * filled + ">" + "." * (self._width - filled)
            line = (f"step {current_num}/{self._num} [{bar}] "
                    f"- {now - self._start:.0f}s - {metrics}")
        else:
            line = f"step {current_num} - {now - self._start:.0f}s - {metrics}"
        end = "\n" if (self._num is not None and current_num >= self._num) \
            else "\r"
        if self._verbose == 1:
            self.file.write("\r" + line + end if end == "\n" else
                            "\r" + line)
        else:
            self.file.write(line + "\n")
        self.file.flush()
        self._last_update = now
