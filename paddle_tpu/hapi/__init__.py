"""High-level training API. Reference analog: python/paddle/hapi/
(model.py:1009 `class Model`, fit :1686; callbacks.py; model_summary.py).

TPU-first: one adapter only — the dygraph adapter (reference keeps a
StaticGraphAdapter at model.py:262 for its legacy graph mode; here "static"
execution is jit capture, so `Model(..).prepare(jit=True)` fuses the whole
train step into a single XLA executable via paddle_tpu.jit.TrainStep).
"""
from .model import Model  # noqa: F401
from .summary import summary  # noqa: F401
from . import callbacks  # noqa: F401
from .progressbar import ProgressBar  # noqa: F401

__all__ = ["Model", "summary", "callbacks", "ProgressBar"]
