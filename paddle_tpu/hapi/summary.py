"""Model summary. Reference analog: python/paddle/hapi/model_summary.py
(`paddle.summary`): per-layer output shapes + parameter counts via forward
hooks on a dummy run."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor
from ..framework import dtype as _dtype_mod

__all__ = ["summary"]


def _num_params(layer):
    return sum(int(np.prod(p.shape)) if p.shape else 1
               for p in layer.parameters(include_sublayers=False))


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params': n,
    'trainable_params': n}."""
    import jax.numpy as jnp

    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = input_size if isinstance(input_size, list) else [input_size]
        sizes = [s if isinstance(s, (list, tuple)) else (s,) for s in sizes]
        if dtypes is None:
            dtypes = ["float32"] * len(sizes)
        elif isinstance(dtypes, str):
            dtypes = [dtypes] * len(sizes)
        inputs = []
        for shape, dt in zip(sizes, dtypes):
            shape = tuple(1 if (d is None or d == -1) else int(d)
                          for d in shape)
            jdt = _dtype_mod.to_jax_dtype(dt)
            if jnp.issubdtype(jdt, jnp.integer):
                arr = jnp.zeros(shape, jdt)
            else:
                arr = jnp.ones(shape, jdt)
            inputs.append(Tensor(arr, stop_gradient=True))
    else:
        inputs = input if isinstance(input, (list, tuple)) else [input]

    rows = []
    hooks = []

    def make_hook(name):
        def hook(layer, inp, out):
            shape = out.shape if isinstance(out, Tensor) else (
                [o.shape for o in out if isinstance(o, Tensor)]
                if isinstance(out, (list, tuple)) else "?")
            rows.append((f"{type(layer).__name__}-{len(rows) + 1}",
                         str(shape), _num_params(layer)))
        return hook

    for name, sub in net.named_sublayers():
        if not list(sub.children()):  # leaves only, like the reference
            hooks.append(sub.register_forward_post_hook(make_hook(name)))
    was_training = net.training
    net.eval()
    try:
        net(*inputs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) if p.shape else 1
                for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) if p.shape else 1
                    for p in net.parameters() if not p.stop_gradient)

    name_w = max([len(r[0]) for r in rows] + [20])
    shape_w = max([len(r[1]) for r in rows] + [20])
    line = "-" * (name_w + shape_w + 16)
    print(line)
    print(f"{'Layer (type)':<{name_w}}  {'Output Shape':<{shape_w}}  Param #")
    print("=" * len(line))
    for r in rows:
        print(f"{r[0]:<{name_w}}  {r[1]:<{shape_w}}  {r[2]:,}")
    print("=" * len(line))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
