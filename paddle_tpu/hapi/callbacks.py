"""Training callbacks. Reference analog: python/paddle/hapi/callbacks.py
(Callback, CallbackList config_callbacks, ProgBarLogger, ModelCheckpoint,
LRScheduler, EarlyStopping, VisualDL, WandbCallback)."""
from __future__ import annotations

import numbers
import os

from .progressbar import ProgressBar

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "ReduceLROnPlateau", "CallbackList",
           "config_callbacks"]


class Callback:
    """Base class; subclass and override the on_* hooks."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, callback):
        self.callbacks.append(callback)

    def __iter__(self):
        return iter(self.callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-step console logging (reference: hapi/callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.train_progbar = None
        self.eval_progbar = None

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.train_metrics = self.params.get("metrics", [])

    def on_epoch_begin(self, epoch, logs=None):
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")
        self.train_progbar = ProgressBar(num=self.params.get("steps"),
                                         verbose=self.verbose)
        self.train_step = 0

    def _updates(self, logs, bar, step):
        values = {k: v for k, v in (logs or {}).items()
                  if isinstance(v, numbers.Number)}
        bar.update(step, values)

    def on_train_batch_end(self, step, logs=None):
        self.train_step = step + 1
        if self.train_step % self.log_freq == 0 and self.verbose:
            self._updates(logs, self.train_progbar, self.train_step)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self._updates(logs, self.train_progbar, self.train_step)

    def on_eval_begin(self, logs=None):
        self.eval_progbar = ProgressBar(num=(logs or {}).get("steps"),
                                        verbose=self.verbose)
        self.eval_step = 0
        if self.verbose:
            print("Eval begin...")

    def on_eval_batch_end(self, step, logs=None):
        self.eval_step = step + 1
        if self.eval_step % self.log_freq == 0 and self.verbose:
            self._updates(logs, self.eval_progbar, self.eval_step)

    def on_eval_end(self, logs=None):
        if self.verbose:
            self._updates(logs, self.eval_progbar, self.eval_step)
            print("Eval samples: ", (logs or {}).get("samples", ""))


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by epoch by default, matching the
    reference's by_epoch=True)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.min_delta = abs(min_delta)
        self.wait_epoch = 0
        self.best_weights = None
        self.stopped_epoch = 0
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self.monitor_op = lambda cur, best: cur < best - self.min_delta
            self.best_value = float("inf")
        else:
            self.monitor_op = lambda cur, best: cur > best + self.min_delta
            self.best_value = -float("inf")

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline

    def on_epoch_end(self, epoch, logs=None):
        self._epoch = epoch

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.monitor_op(current, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model and self.model is not None and \
                    self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"],
                                             "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch >= self.patience:
            self.stopped_epoch = getattr(self, "_epoch", 0)
            if self.model is not None:
                self.model.stop_training = True
            if self.verbose:
                print(f"Epoch {self.stopped_epoch}: Early stopping.")


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.cooldown_counter = 0
        self.wait = 0
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.monitor_op = lambda a, b: a > b + self.min_delta
            self.best = -float("inf")
        else:
            self.monitor_op = lambda a, b: a < b - self.min_delta
            self.best = float("inf")

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self.monitor_op(current, self.best):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    old = opt.get_lr()
                    new = max(old * self.factor, self.min_lr)
                    if old - new > 1e-12:
                        opt.set_lr(new)
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr {old} -> {new}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Scalar logging to a directory of JSONL files (the VisualDL service is
    GPU-ecosystem tooling; on TPU pods the same role is played by TensorBoard
    over the jax profiler — this keeps the API and writes portable logs)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._fh = None
        self._step = 0

    def _write(self, tag, logs, step):
        import json
        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                self._fh.write(json.dumps(
                    {"tag": f"{tag}/{k}", "value": float(v),
                     "step": step}) + "\n")
        self._fh.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._write("train", logs, self._step)

    def on_eval_end(self, logs=None):
        self._write("eval", logs, self._step)

    def on_train_end(self, logs=None):
        if self._fh:
            self._fh.close()
            self._fh = None


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    params = {"batch_size": batch_size, "epochs": epochs, "steps": steps,
              "verbose": verbose, "metrics": metrics or [],
              "save_dir": save_dir}
    cbk_list.set_params(params)
    return cbk_list
