"""Keras-like Model facade. Reference analog: python/paddle/hapi/model.py:1009
(`class Model`; fit :1686; DynamicGraphAdapter :737).

TPU-first: a single dygraph adapter whose train step can optionally be fused
into one XLA executable (`prepare(..., jit=True)` → paddle_tpu.jit.TrainStep),
replacing the reference's dual static/dynamic adapters."""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..framework.core import Tensor
from ..framework import io as _io
from ..metric import Metric
from .callbacks import config_callbacks

__all__ = ["Model"]


def _to_tensor_list(data):
    if data is None:
        return []
    if isinstance(data, (list, tuple)):
        return [d if isinstance(d, Tensor) else Tensor(np.asarray(d))
                for d in data]
    return [data if isinstance(data, Tensor) else Tensor(np.asarray(data))]


def _to_numpy(x):
    if isinstance(x, Tensor):
        return x.numpy()
    return np.asarray(x)


class Model:
    """Wraps a `nn.Layer` with train/eval/predict loops.

    model = Model(network)
    model.prepare(optimizer, loss, metrics)
    model.fit(train_dataset, eval_dataset, epochs=2, batch_size=32)
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._use_jit_step = False
        self._train_step = None
        self.stop_training = False

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit=False):
        self._optimizer = optimizer
        self._loss = loss
        metrics = metrics or []
        if not isinstance(metrics, (list, tuple)):
            metrics = [metrics]
        for m in metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle.metric.Metric")
        self._metrics = list(metrics)
        self._use_jit_step = bool(jit)
        self._train_step = None

    # ------------------------------------------------------------- batches
    def train_batch(self, inputs, labels=None, update=True, loss_scale=1.0):
        """One optimization step; returns (loss_values, metric_results).
        update=False accumulates gradients without stepping (loss scaled by
        loss_scale so k accumulated micro-batches average)."""
        self.network.train()
        inputs = _to_tensor_list(inputs)
        labels = _to_tensor_list(labels)
        # the fused jit step returns only the loss and applies grads
        # functionally, so metrics and gradient accumulation (scaled partial
        # backward) need the eager path
        if self._use_jit_step and self._loss is not None and update and \
                not self._metrics and loss_scale == 1.0:
            from ..jit.train_step import TrainStep
            if self._train_step is None:
                self._train_step = TrainStep(self.network, self._loss,
                                             self._optimizer)
            loss = self._train_step(*inputs, *labels)
            return [float(loss)], []
        outputs = self.network(*inputs)
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        if self._loss is not None:
            loss = self._loss(*outs, *labels)
        else:
            loss = outs[0]
        if self._optimizer is not None:
            (loss * loss_scale if loss_scale != 1.0 else loss).backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        metric_res = []
        for m in self._metrics:
            res = m.compute(outs[0], *labels)
            if isinstance(res, Tensor):
                res = res.numpy()
            m.update(res)
            metric_res.append(m.accumulate())
        return [float(loss)], metric_res

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..framework.autograd import no_grad
        with no_grad():
            inputs = _to_tensor_list(inputs)
            labels = _to_tensor_list(labels)
            outputs = self.network(*inputs)
            outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
            losses = []
            if self._loss is not None and labels:
                losses = [float(self._loss(*outs, *labels))]
            metric_res = []
            for m in self._metrics:
                res = m.compute(outs[0], *labels)
                if isinstance(res, Tensor):
                    res = res.numpy()
                m.update(res)
                metric_res.append(m.accumulate())
            return losses, metric_res

    def predict_batch(self, inputs):
        self.network.eval()
        from ..framework.autograd import no_grad
        with no_grad():
            outputs = self.network(*_to_tensor_list(inputs))
            if isinstance(outputs, (list, tuple)):
                return [_to_numpy(o) for o in outputs]
            return _to_numpy(outputs)

    # ------------------------------------------------------------- loops
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        from ..io import DataLoader
        if data is None or isinstance(data, DataLoader):
            return data
        if hasattr(data, "__iter__") and not hasattr(data, "__getitem__"):
            return data  # generator-style iterable
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    @staticmethod
    def _split_batch(batch):
        """hapi convention: last element of the batch tuple is the label."""
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return [batch], []

    def _metric_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, (list, tuple)) else [n])
        return names

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        assert train_data is not None, "train_data must be given!"
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        import types
        if epochs > 1 and isinstance(loader, types.GeneratorType):
            raise ValueError(
                "train_data is a one-shot generator but epochs > 1; pass a "
                "Dataset/DataLoader or a re-iterable so every epoch has data")
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers, False)
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                save_freq=save_freq, save_dir=save_dir,
                                verbose=verbose,
                                metrics=self._metric_names())
        self.stop_training = False
        cbks.on_train_begin()
        for epoch in range(epochs):
            if self.stop_training:
                break
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            for step, batch in enumerate(loader):
                if num_iters is not None and step >= num_iters:
                    break
                cbks.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                k = max(1, accumulate_grad_batches)
                losses, metrics = self.train_batch(
                    ins, labs, update=((step + 1) % k == 0),
                    loss_scale=1.0 / k)
                logs = {"loss": losses[0]}
                for m, res in zip(self._metrics, metrics):
                    n = m.name()
                    names = n if isinstance(n, (list, tuple)) else [n]
                    vals = res if isinstance(res, (list, tuple)) else [res]
                    logs.update(zip(names, vals))
                cbks.on_train_batch_end(step, logs)
            k = max(1, accumulate_grad_batches)
            if k > 1 and (step + 1) % k != 0 and self._optimizer is not None:
                # flush the trailing partial accumulation window so no scaled
                # gradients leak into the next epoch
                self._optimizer.step()
                self._optimizer.clear_grad()
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks,
                              num_workers=num_workers)
        cbks.on_train_end(logs)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers,
                                   False)
        own_cbks = not isinstance(callbacks, type(None)) and \
            hasattr(callbacks, "on_eval_begin")
        cbks = callbacks if own_cbks else config_callbacks(
            callbacks, model=self, log_freq=log_freq, verbose=verbose,
            metrics=self._metric_names(), mode="eval")
        for m in self._metrics:
            m.reset()
        steps = len(loader) if hasattr(loader, "__len__") else None
        cbks.on_eval_begin({"steps": steps})
        logs = {}
        samples = 0
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            losses, metrics = self.eval_batch(ins, labs)
            if losses:
                logs["loss"] = losses[0]
            for m, res in zip(self._metrics, metrics):
                n = m.name()
                names = n if isinstance(n, (list, tuple)) else [n]
                vals = res if isinstance(res, (list, tuple)) else [res]
                logs.update(zip(names, vals))
            samples += ins[0].shape[0] if ins and ins[0].shape else 1
            cbks.on_eval_batch_end(step, logs)
        logs["samples"] = samples
        cbks.on_eval_end(logs)
        return {k: v for k, v in logs.items() if k != "samples"}

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers,
                                   False)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch) if isinstance(batch, (list, tuple)) \
                else ([batch], [])
            out = self.predict_batch(ins)
            outputs.append(out)
        if stack_outputs and outputs:
            if isinstance(outputs[0], list):
                outputs = [np.concatenate([o[i] for o in outputs])
                           for i in range(len(outputs[0]))]
            else:
                outputs = np.concatenate(outputs)
        return outputs

    # ------------------------------------------------------------- io
    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        _io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = _io.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(_io.load(opt_path))

    # ------------------------------------------------------------- misc
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtype)
