"""paddle.sysconfig equivalent: include/lib paths for building extensions
against the native runtime (csrc/). Reference analog:
python/paddle/sysconfig.py."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def get_include():
    """Directory of the native runtime sources/headers (csrc/)."""
    return os.path.join(_ROOT, "csrc")


def get_lib():
    """Directory holding the built native libraries (.so)."""
    from .core._build import _cache_dir
    return _cache_dir()
