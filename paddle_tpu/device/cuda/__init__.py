"""paddle.device.cuda — CUDA device API surface (reference:
python/paddle/device/cuda). There is no CUDA on a TPU host: queries report
zero devices, stream/event objects are inert (XLA owns streams), and
allocation probes return 0 — feature-detecting user code takes its
CPU/other-device path naturally instead of crashing on import."""
from __future__ import annotations

__all__ = ["Stream", "Event", "current_stream", "synchronize",
           "device_count", "empty_cache", "max_memory_allocated",
           "max_memory_reserved", "memory_allocated", "memory_reserved",
           "stream_guard", "get_device_properties", "get_device_name",
           "get_device_capability"]


def device_count():
    return 0


def synchronize(device=None):
    return None


def empty_cache():
    return None


def max_memory_allocated(device=None):
    return 0


def max_memory_reserved(device=None):
    return 0


def memory_allocated(device=None):
    return 0


def memory_reserved(device=None):
    return 0


class Stream:
    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        return None

    def wait_event(self, event):
        return None

    def wait_stream(self, stream):
        return None

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        return None

    def query(self):
        return True

    def synchronize(self):
        return None


def current_stream(device=None):
    return Stream(device)


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


def _no_cuda(what):
    raise RuntimeError(
        f"{what}: no CUDA device on a TPU host (device_count() == 0)")


def get_device_properties(device=None):
    _no_cuda("get_device_properties")


def get_device_name(device=None):
    _no_cuda("get_device_name")


def get_device_capability(device=None):
    _no_cuda("get_device_capability")
