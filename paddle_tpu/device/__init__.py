"""Device API. Reference analog: python/paddle/device/__init__.py
(set_device :328, get_all_custom_device_type :427) over phi Place/DeviceManager.

TPU-first: devices are jax devices; XLA owns streams/allocators, so this module
is a thin selection/query layer (SURVEY.md §7 translation table row 2).
"""
from __future__ import annotations

import jax

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_all_custom_device_type", "get_available_device",
           "device_count", "is_compiled_with_cuda", "is_compiled_with_rocm",
           "is_compiled_with_xpu", "is_compiled_with_npu",
           "is_compiled_with_custom_device", "CPUPlace", "CUDAPlace",
           "TPUPlace", "CUDAPinnedPlace", "XLADevice", "synchronize"]

_current_device = None


class _PlaceBase:
    device_type = "cpu"

    def __init__(self, device_id=0):
        self._device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self._device_id})"

    def __eq__(self, other):
        return (type(self) is type(other) and
                self._device_id == other._device_id)

    def get_device_id(self):
        return self._device_id


class CPUPlace(_PlaceBase):
    device_type = "cpu"


class TPUPlace(_PlaceBase):
    device_type = "tpu"


class CUDAPlace(_PlaceBase):
    # accepted for API parity; maps onto the default accelerator
    device_type = "gpu"


class CUDAPinnedPlace(_PlaceBase):
    device_type = "cpu"


class XLADevice:
    """Wrapper over a jax.Device."""

    def __init__(self, jax_device):
        self.jax_device = jax_device

    def __repr__(self):
        return f"XLADevice({self.jax_device.platform}:{self.jax_device.id})"


def _platform():
    return jax.devices()[0].platform


def set_device(device):
    """Accepts 'cpu', 'tpu', 'tpu:0', 'gpu:0' (mapped to default accelerator)."""
    global _current_device
    name = device if isinstance(device, str) else getattr(
        device, "device_type", "cpu")
    _current_device = name
    return get_device()


def get_device():
    if _current_device is not None:
        return _current_device
    p = _platform()
    canonical = {"axon": "tpu"}.get(p, p)
    return f"{canonical}:0"


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [p for p in get_all_device_type() if p not in ("cpu", "gpu")]


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count():
    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_custom_device(device_type="tpu"):
    return device_type in get_all_device_type() or \
        ("tpu" == device_type and _platform() == "axon")


def synchronize():
    """Block until all enqueued device work completes."""
    for d in jax.live_arrays():
        d.block_until_ready()


class NPUPlace(_PlaceBase):
    """Parity shims for the reference's vendor places (no such backends
    here; they exist so configs naming them still parse)."""
    device_type = "npu"


class XPUPlace(_PlaceBase):
    device_type = "xpu"


class MLUPlace(_PlaceBase):
    device_type = "mlu"


class IPUPlace(_PlaceBase):
    device_type = "ipu"


def get_cudnn_version():
    """No cuDNN in the TPU build (reference: device/__init__.py returns
    None when not compiled with CUDA)."""
    return None


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_mlu():
    return False


def get_available_custom_device():
    """Custom-device inventory (reference: device/__init__.py) — the TPU
    build's accelerators surface through jax."""
    import jax
    try:
        return [f"{d.platform}:{d.id}" for d in jax.devices()
                if d.platform not in ("cpu",)]
    except RuntimeError:
        return []


__all__ += ["get_cudnn_version", "is_compiled_with_ipu",
            "is_compiled_with_cinn", "is_compiled_with_mlu",
            "get_available_custom_device"]
