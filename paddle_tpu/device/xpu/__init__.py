"""paddle.device.xpu shim (reference: python/paddle/device/xpu) — no
Kunlun XPU on a TPU host."""
__all__ = ["synchronize"]


def synchronize(device=None):
    return None
