"""Multi-tenant serving primitives: shared-prefix KV reuse, batched
LoRA-style adapters, live weight hot-swap staging (PR 17).

Reference analog: the reference's parameter-server shape — "multiple
programs, one runtime" — serves many logical models off one resident
process. This module is that idea rebuilt for the PR 6 serving engine's
single compiled decode step:

  * `PrefixCache` — a content-addressed index over the paged block pool
    (serving/cache.py). Prompt-aligned FULL blocks key by a rolling
    chain digest (h_i = digest(h_{i-1}, block tokens)) so a lookup walks
    the chain dict-hit by dict-hit; partial tails key under their parent
    chain with the exact token tuple, and a lookup may also use the
    leading tokens of a published block (common-prefix scan of the
    parent's children), which is what makes copy-on-write REAL: a
    sequence admitted onto a shared tail writes its next token's KV
    into a block other owners still read, so the engine COWs that one
    block first. The index holds its OWN reference on every published
    block (BlockAllocator refcounts), so entries survive their
    publisher's completion and are reclaimed leaf-first, least-popular
    first, when the pool runs dry: eviction orders leaves by an AGED
    hit count (halved every `_AGE_PERIOD` lookups, so popularity
    decays) with last-use recency as the tie-break — a cold tenant's
    burst evicts its own blocks, never the hot shared system prompt. A match is capped at context_len - 1
    tokens: there is always at least one input token to feed, so the
    decode step (never the prefill path) produces the first sampled
    token and greedy decode stays token-identical to the cold path.

  * `AdapterSet` — per-tenant low-rank deltas batched as VALUE inputs
    to the ONE compiled decode executable. All adapters live in fixed
    padded stacks (``[K, L, in, r]`` / ``[K, L, r, out]`` per target
    projection, K = max_adapters + 1 with slot 0 the all-zeros base),
    so tenants joining/leaving/churning only change array VALUES and a
    per-batch-slot int32 index — zero retraces. The delta applies at
    the activation level (``y + (x @ A) @ B * scale``) through
    instance-level forwards installed on the attention projections;
    with the context unarmed the wrapper is the original forward
    bit-for-bit, so training and `model.generate` never see it.

Lock discipline (analysis/rules/r6_lock_discipline.py applies to this
file): every refcount/index mutation happens under the owning lock;
snapshots are taken under the lock and ALL side effects — flight
recorder events, metrics, device copies — happen after release. Never
call back into user code with a lock held.
"""
from __future__ import annotations

import threading
import zlib

import numpy as np
import jax.numpy as jnp

__all__ = ["PrefixCache", "AdapterSet"]

# chain root for the first block of every prompt
_ROOT = "prefix:root"


def _digest(parent, tokens):
    """Rolling chain digest: stable across processes (crc32, not
    Python's salted hash) so a future shared index could persist."""
    h = zlib.crc32(repr(parent).encode())
    h = zlib.crc32(repr(tuple(int(t) for t in tokens)).encode(), h)
    return h


# acquires between hit-count halvings: aging keeps yesterday's hot
# prompt from squatting on blocks today's traffic needs, without
# forgetting a genuinely popular prefix the moment it pauses
_AGE_PERIOD = 256


class _Entry:
    __slots__ = ("key", "parent", "block", "tokens", "hits", "tick")

    def __init__(self, key, parent, block, tokens, tick=0):
        self.key = key
        self.parent = parent
        self.block = block
        self.tokens = tokens      # the tokens whose KV this block holds
        self.hits = 0             # aged popularity (halved every period)
        self.tick = tick          # last-use tick (recency tie-break)


class PrefixCache:
    """Content-hash index of prompt-aligned block runs in the paged pool.

    `acquire(tokens)` returns the longest cached run matching a prompt
    prefix — already increfed, ready to alias into a block table;
    `publish(tokens, blocks)` indexes a freshly prefilled prompt's
    blocks (increfing them on behalf of the index); `reclaim(n)` drops
    cold entries leaf-first — least aged-hit-count first, recency as
    tie-break — until the allocator can serve `n` free blocks. `invalidate()` empties the index (weight hot-swap:
    cached KV is a function of the base weights); `reset(allocator)`
    rebinds after the engine rebuilt the pool (the old refs died with
    the old allocator).
    """

    def __init__(self, allocator, block_size):
        self.allocator = allocator
        self.block_size = int(block_size)
        self._lock = threading.Lock()
        self._entries = {}          # key -> _Entry
        self._children = {}         # parent key -> {key: _Entry}
        self._tick = 0              # lookup clock for recency ordering
        self.hits = 0
        self.misses = 0

    # -- introspection ------------------------------------------------------
    @property
    def entries(self):
        with self._lock:
            return len(self._entries)

    @property
    def blocks_held(self):
        with self._lock:
            return len(self._entries)   # one block per entry

    # -- lookup -------------------------------------------------------------
    def _walk(self, tokens):
        """Longest cached run covering a strict prefix of `tokens`
        (capped at len-1 so one input token always remains). Caller
        holds the lock. Returns (entries, hit_tokens)."""
        bs = self.block_size
        limit = len(tokens) - 1
        path, hit, parent = [], 0, _ROOT
        i = 0
        while (i + 1) * bs <= limit:
            key = ("b", _digest(parent, tokens[i * bs:(i + 1) * bs]))
            e = self._entries.get(key)
            if e is None:
                break
            path.append(e)
            parent = key
            hit += bs
            i += 1
        # partial step: the longest common prefix between the remaining
        # tokens and any published child (a tail entry, or the leading
        # tokens of a full block) — THE copy-on-write case: the next
        # write lands inside this still-shared block
        rest = tokens[hit:limit]
        best, best_t = None, 0
        for e in self._children.get(parent, {}).values():
            t = 0
            for a, b in zip(e.tokens, rest):
                if int(a) != int(b):
                    break
                t += 1
            if t > best_t:
                best, best_t = e, t
        if best is not None and best_t > 0:
            path.append(best)
            hit += best_t
        return path, hit

    def probe(self, tokens):
        """Non-acquiring feasibility probe: (shared_block_count,
        hit_tokens) for `can_ever_fit` / admission-policy sizing. Takes
        no references — the answer is advisory and may differ by the
        time admission runs."""
        with self._lock:
            path, hit = self._walk(list(tokens))
            if not self._usable(hit, len(tokens)):
                return 0, 0
            return len(path), hit

    def _usable(self, hit, prompt_len):
        # a hit below one block (unless it covers the whole cacheable
        # prompt) saves less prefill than its chew steps cost
        return hit > 0 and (hit >= self.block_size
                            or hit == prompt_len - 1)

    def _touch(self, e):
        """One use of an entry: bump its aged hit count and recency
        tick. Caller holds the lock."""
        e.hits += 1
        e.tick = self._tick

    def _advance_clock(self):
        """Bump the lookup clock; every `_AGE_PERIOD` ticks halve all
        hit counts so popularity DECAYS — an entry hot last epoch but
        cold now loses its eviction immunity. Caller holds the lock."""
        self._tick += 1
        if self._tick % _AGE_PERIOD == 0:
            for e in self._entries.values():
                e.hits >>= 1

    def acquire(self, tokens):
        """Longest cached run for a prompt prefix, INCREFED for the
        caller (one reference per block — symmetric with
        `allocator.free`). Returns (blocks, hit_tokens); ([], 0) on a
        miss. Touches the matched entries' hit count + recency."""
        tokens = list(tokens)
        with self._lock:
            self._advance_clock()
            path, hit = self._walk(tokens)
            if not self._usable(hit, len(tokens)):
                self.misses += 1
                return [], 0
            blocks = []
            for e in path:
                self.allocator.incref(e.block)
                blocks.append(e.block)
                self._touch(e)
            self.hits += 1
            return blocks, hit

    # -- publication --------------------------------------------------------
    def publish(self, tokens, blocks, include_tail=True):
        """Index a freshly prefilled prompt's aligned blocks. Every NEW
        entry increfs its block on behalf of the index (the index is an
        owner like any sequence). `include_tail=False` skips the
        partial last block (resume prefills write generated-token KV
        into it, which must never be served as prompt KV). Returns the
        number of entries added."""
        tokens = list(tokens)
        bs = self.block_size
        added = 0
        with self._lock:
            parent = _ROOT
            n_full = len(tokens) // bs
            for i in range(n_full):
                chunk = tokens[i * bs:(i + 1) * bs]
                key = ("b", _digest(parent, chunk))
                if key not in self._entries:
                    if i >= len(blocks):
                        break
                    self.allocator.incref(blocks[i])
                    e = _Entry(key, parent, blocks[i], tuple(chunk),
                               tick=self._tick)
                    self._entries[key] = e
                    self._children.setdefault(parent, {})[key] = e
                    added += 1
                parent = key
            tail = tokens[n_full * bs:]
            if include_tail and tail and n_full < len(blocks):
                key = ("t", _digest(parent, tail), len(tail))
                if key not in self._entries:
                    self.allocator.incref(blocks[n_full])
                    e = _Entry(key, parent, blocks[n_full], tuple(tail),
                               tick=self._tick)
                    self._entries[key] = e
                    self._children.setdefault(parent, {})[key] = e
                    added += 1
        return added

    # -- reclaim / invalidation ---------------------------------------------
    def _drop(self, e):
        """Remove one entry and release the index's reference. Caller
        holds the lock."""
        self._entries.pop(e.key, None)
        kids = self._children.get(e.parent)
        if kids:
            kids.pop(e.key, None)
            if not kids:
                del self._children[e.parent]
        self.allocator.free([e.block])

    def reclaim(self, num_free_target):
        """Release cold entries until the allocator has
        `num_free_target` free blocks or the index is empty. Victims
        are leaves (dropping an interior entry would orphan its chain)
        ordered by (aged hit count, last-use tick): the least-popular
        leaf goes first, recency breaks ties — so one cold tenant's
        burst evicts ITS blocks, not the hot shared system prompt that
        a plain LRU scan would rotate out. Returns the number of
        entries dropped — the caller emits the `serve.prefix_evict`
        attribution AFTER this returns (no events under the lock)."""
        dropped = 0
        with self._lock:
            while self.allocator.num_free < num_free_target:
                victim, best = None, None
                for e in self._entries.values():
                    if self._children.get(e.key):
                        continue              # interior: kids pin it
                    score = (e.hits, e.tick)
                    if best is None or score < best:
                        victim, best = e, score
                if victim is None:
                    break
                self._drop(victim)
                dropped += 1
        return dropped

    def invalidate(self):
        """Empty the index, releasing every reference it holds — the
        weight hot-swap path: cached KV is a function of the base
        weights, so a new weight epoch starts cold. Returns the number
        of entries released."""
        with self._lock:
            n = len(self._entries)
            for e in list(self._entries.values()):
                self.allocator.free([e.block])
            self._entries.clear()
            self._children.clear()
        return n

    def reset(self, allocator):
        """Forget everything WITHOUT releasing references — the engine
        rebuilt the pool (`_reset_kv_state`) and the old allocator died
        with the old blocks."""
        with self._lock:
            self._entries.clear()
            self._children.clear()
            self.allocator = allocator


class AdapterSet:
    """Per-tenant LoRA-style deltas batched into fixed padded stacks.

    Targets the attention projections (`qkv_proj`, `out_proj`) of every
    layer. For each target the set owns ``A [K, L, in, r]`` and
    ``B [K, L, r, out]`` plus ``scale [K]``, K = max_adapters + 1 —
    slot 0 is the reserved all-zeros BASE adapter, whose delta is
    exactly 0.0 (not merely small), so base tenants stay bit-identical
    to the adapter-free engine. Registration writes VALUES into the
    stacks; the compiled decode/prefill programs take the stacks and a
    per-batch-slot index as inputs, so tenant churn never retraces.

    All registry mutations happen under `self._lock`; the stacks are
    swapped whole (copy-on-write on the host arrays) so a compiled call
    mid-flight never sees a half-written slot.
    """

    def __init__(self, model, max_adapters, rank, dtype=None):
        cfg = model.config
        if max_adapters < 1:
            raise ValueError("max_adapters must be >= 1")
        if rank < 1:
            raise ValueError("adapter rank must be >= 1")
        self.model = model
        self.max_adapters = int(max_adapters)
        self.rank = int(rank)
        self.num_layers = int(cfg.num_hidden_layers)
        hidden = int(cfg.hidden_size)
        if dtype is None:
            params = model.parameters()
            dtype = (np.asarray(params[0]._value).dtype if params
                     else np.float32)
        self.dtype = np.dtype(dtype)
        k = self.max_adapters + 1
        l, r = self.num_layers, self.rank
        # target name -> (in_features, out_features)
        self.targets = {"qkv": (hidden, 3 * hidden),
                        "out": (hidden, hidden)}
        self._a = {t: np.zeros((k, l, i, r), self.dtype)
                   for t, (i, _) in self.targets.items()}
        self._b = {t: np.zeros((k, l, r, o), self.dtype)
                   for t, (_, o) in self.targets.items()}
        self._scale = np.zeros(k, np.float32)
        self._lock = threading.Lock()
        self._names = {}            # name -> slot (1..max_adapters)
        self._device = None         # cached jnp views of the stacks

    # -- registry -----------------------------------------------------------
    def names(self):
        with self._lock:
            return sorted(self._names)

    def slot_of(self, name):
        """Stack slot for an adapter name (0 = base for None)."""
        if name is None:
            return 0
        with self._lock:
            slot = self._names.get(name)
        if slot is None:
            raise KeyError(f"adapter {name!r} is not registered")
        return slot

    def is_registered(self, name):
        if name is None:
            return True
        with self._lock:
            return name in self._names

    def register(self, name, weights=None, scale=1.0, seed=None):
        """Install an adapter into a free slot. `weights` maps target
        name ("qkv"/"out") to an ``(A [L, in, r], B [L, r, out])``
        pair; with `weights=None` both factors draw from a seeded
        normal (handy for tests/benches — note real LoRA inits B to
        zero, which would make the delta vanish). Returns the slot."""
        if name is None:
            raise ValueError("adapter name must be a non-empty string")
        new_a = {t: None for t in self.targets}
        new_b = {t: None for t in self.targets}
        for t, (i, o) in self.targets.items():
            if weights is not None:
                a, b = weights[t]
                a = np.asarray(a, self.dtype)
                b = np.asarray(b, self.dtype)
            else:
                rng = np.random.default_rng(
                    zlib.crc32(f"{name}:{t}:{seed}".encode()))
                a = rng.normal(0.0, 0.05,
                               (self.num_layers, i, self.rank)) \
                    .astype(self.dtype)
                b = rng.normal(0.0, 0.05,
                               (self.num_layers, self.rank, o)) \
                    .astype(self.dtype)
            want_a = (self.num_layers, i, self.rank)
            want_b = (self.num_layers, self.rank, o)
            if a.shape != want_a or b.shape != want_b:
                raise ValueError(
                    f"adapter {name!r} target {t!r}: want A{want_a} / "
                    f"B{want_b}, got A{a.shape} / B{b.shape}")
            new_a[t], new_b[t] = a, b
        with self._lock:
            if name in self._names:
                raise ValueError(f"adapter {name!r} is already registered")
            used = set(self._names.values())
            slot = next((s for s in range(1, self.max_adapters + 1)
                         if s not in used), None)
            if slot is None:
                raise ValueError(
                    f"all {self.max_adapters} adapter slots are in use")
            for t in self.targets:
                self._a[t][slot] = new_a[t]
                self._b[t][slot] = new_b[t]
            self._scale[slot] = float(scale)
            self._names[name] = slot
            self._device = None
        return slot

    def unregister(self, name):
        """Free an adapter's slot (zeroing it — the stack VALUES change,
        the shapes never do). The caller ensures no live stream still
        decodes under it."""
        with self._lock:
            slot = self._names.pop(name, None)
            if slot is None:
                raise KeyError(f"adapter {name!r} is not registered")
            for t in self.targets:
                self._a[t][slot] = 0
                self._b[t][slot] = 0
            self._scale[slot] = 0.0
            self._device = None
        return slot

    # -- compiled-program inputs --------------------------------------------
    def device_stacks(self):
        """The padded stacks as ONE flat tuple of arrays — the decode/
        prefill executables' adapter VALUE inputs. Shapes are fixed at
        construction (K, L, r baked), so churn never re-keys. Cached
        until the registry next mutates."""
        with self._lock:
            dev = self._device
            if dev is None:
                dev = tuple(jnp.asarray(x) for x in (
                    self._a["qkv"], self._b["qkv"],
                    self._a["out"], self._b["out"], self._scale))
                self._device = dev
        return dev

    @staticmethod
    def trace_ctx(stacks, slots=None, slot=None):
        """Arm the projection wrappers for one traced call: `slots` is
        the per-batch-slot adapter index ([S] int32, decode), `slot` a
        scalar index (prefill)."""
        a_qkv, b_qkv, a_out, b_out, scale = stacks
        return {"a": {"qkv": a_qkv, "out": a_out},
                "b": {"qkv": b_qkv, "out": b_out},
                "scale": scale, "slots": slots, "slot": slot}

    # -- model wiring -------------------------------------------------------
    def install(self, holder):
        """Install activation-level wrappers on every target projection.
        `holder` is a mutable dict shared with the engine's compiled
        programs: `holder["active"]` is None outside a tenant trace (the
        wrapper then IS the original forward), or a `trace_ctx` dict
        whose arrays are the current trace's value inputs. Idempotent
        per model."""
        if getattr(self.model, "_tenancy_wrapped", False):
            return
        for layer_idx, block in enumerate(self.model.gpt.h):
            for tname, lin in (("qkv", block.attn.qkv_proj),
                               ("out", block.attn.out_proj)):
                lin.forward = _adapter_forward(lin, layer_idx, tname,
                                               holder)
        self.model._tenancy_wrapped = True

    # -- eager merge (degraded-mode fallback) -------------------------------
    def merged(self, name):
        """Context manager: fold one adapter into the target weights
        (``W + A @ B * scale``) for the eager `model.generate` fallback
        path, restoring the base weights on exit. `model.generate`
        passes parameters as VALUES, so the merge never retraces its
        cached program. Note the merge is mathematically — not
        bitwise — equal to the activation-level delta (matmul
        associativity), which is exactly the fallback contract the
        compiled path also honors for the base slot (whose delta is an
        exact 0.0)."""
        return _MergedAdapter(self, name)


class _MergedAdapter:
    def __init__(self, adapters, name):
        self._adapters = adapters
        self._name = name
        self._saved = []

    def __enter__(self):
        ad = self._adapters
        slot = ad.slot_of(self._name)
        if slot == 0:
            return self
        scale = float(ad._scale[slot])
        for layer_idx, block in enumerate(ad.model.gpt.h):
            for tname, lin in (("qkv", block.attn.qkv_proj),
                               ("out", block.attn.out_proj)):
                w = lin.weight._value
                self._saved.append((lin, w))
                delta = (ad._a[tname][slot, layer_idx]
                         @ ad._b[tname][slot, layer_idx]) * scale
                lin.weight._value = w + jnp.asarray(delta).astype(w.dtype)
        return self

    def __exit__(self, *exc):
        for lin, w in self._saved:
            lin.weight._value = w
        self._saved = []
        return False


def _adapter_forward(lin, layer_idx, tname, holder):
    """Instance-level forward for one target projection: the original
    linear plus the slot-gathered low-rank delta when a tenant trace is
    active; the original linear exactly otherwise."""
    from ..nn import functional as F
    from ..framework.core import Tensor

    def forward(x):
        y = F.linear(x, lin.weight, lin.bias)
        ctx = holder.get("active")
        if ctx is None:
            return y
        a = ctx["a"][tname]
        b = ctx["b"][tname]
        scale = ctx["scale"]
        xv = x._value if hasattr(x, "_value") else jnp.asarray(x)
        if ctx["slots"] is not None:
            # decode: every batch slot gathers ITS tenant's factors
            sl = ctx["slots"]
            av = a[sl, layer_idx]               # [S, in, r]
            bv = b[sl, layer_idx]               # [S, r, out]
            sc = scale[sl].astype(xv.dtype)     # [S]
            delta = jnp.einsum("sni,sir->snr", xv, av)
            delta = jnp.einsum("snr,sro->sno", delta, bv) \
                * sc[:, None, None]
        else:
            # prefill: one request, scalar slot index
            idx = ctx["slot"]
            av = a[idx, layer_idx]
            bv = b[idx, layer_idx]
            delta = (xv @ av) @ bv \
                * scale[idx].astype(xv.dtype)
        return Tensor(y._value + delta.astype(y._value.dtype),
                      stop_gradient=True)

    return forward
