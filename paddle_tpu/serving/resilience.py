"""Serving resilience primitives: structured refusal, the hung-step
watchdog, and crash-resume snapshots.

PR 6 built the serving happy path (continuous batching, paged KV, ONE
compiled decode step); this module is the failure-handling layer that
makes it a "millions of users" component:

  * `ServeRefusal` — the structured admission refusal. Subclasses
    ValueError (the PR 6 refusal type) so existing callers keep working,
    but carries a machine-readable `reason` from the flight-recorder
    contract (`queue_full` / `deadline_infeasible` / `kv_exhausted`)
    plus a `detail` dict mirroring the emitted `serve.refuse` event.
    Refusing early is the whole point of backpressure: work that would
    expire unserved is bounced at the door, not queued to rot.

  * `MonitoredWait` — bounded completion for a decode/prefill fire. The
    engine dispatches the step (async), then waits for the result
    arrays through `wait()`: an `is_ready()` poll that YIELDS
    (`time.sleep(0)`) between checks against the
    `FLAGS_serve_step_timeout_ms` deadline, escalating to millisecond
    sleeps once a step is clearly slow. The yield is the load-bearing
    part: a hard spin competes with XLA's own compute threads and taxes
    the very step it watches (measured ~30%/step on a 2-core box),
    while yield-polling benchmarks AT or BELOW the cost of the plain
    blocking read it replaces — the <3%/step perf_smoke guard pins
    this. No waiter threads: a cross-thread handoff costs 2+ context
    switches per step (~10x the guard budget) and a wedged waiter could
    not be cancelled anyway. Chaos hang faults
    (`guardian.inject_fault("hang", op="serve.decode")`) short-circuit
    the wait so the recovery ladder is testable without wedging a real
    device.

  * snapshot helpers — `request_payload` / `payload_request` serialize a
    Request's RESUMABLE identity (prompt, emitted tokens, arrival order,
    remaining TTL — not the KV pool: resume re-prefills through the
    PR 6 token-identical machinery). The engine composes these into one
    JSON-able engine snapshot saved on the StepCheckpointer's
    atomic/CRC machinery (incubate.checkpoint.ServeCheckpointer), so a
    kill-9'd server restarts and finishes every in-flight stream
    byte-identically (tools/chaos.py `serve_kill` proves it).
"""
from __future__ import annotations

import time

from ..framework.flags import _FLAGS
from .scheduler import Request

__all__ = ["ServeRefusal", "MonitoredWait", "StepHang", "watchdog_budget_s",
           "request_payload", "payload_request"]


class ServeRefusal(ValueError):
    """Admission refused with a machine-readable reason.

    `reason` is a flight-recorder reason code (`queue_full` /
    `deadline_infeasible` / `kv_exhausted`); `detail` mirrors the
    `serve.refuse` event payload. ValueError subclass: PR 6 callers that
    caught ValueError on admission keep working unchanged.
    """

    def __init__(self, reason, message, detail=None):
        super().__init__(message)
        self.reason = reason
        self.detail = dict(detail or {})


class StepHang(RuntimeError):
    """A monitored decode/prefill step blew the watchdog budget."""

    def __init__(self, phase, budget_ms, attempt):
        super().__init__(
            f"serving {phase} step exceeded the "
            f"FLAGS_serve_step_timeout_ms budget ({budget_ms} ms, "
            f"attempt {attempt})")
        self.phase = phase
        self.budget_ms = budget_ms
        self.attempt = attempt


def watchdog_budget_s():
    """The armed watchdog budget in seconds, or None when disarmed."""
    try:
        ms = float(_FLAGS.get("FLAGS_serve_step_timeout_ms", 0) or 0)
    except (TypeError, ValueError):
        ms = 0.0
    return ms / 1e3 if ms > 0 else None


# a step still pending after this long is no longer latency-critical:
# switch from yield-polling to millisecond sleeps so a slow-but-alive
# device (or a genuine hang burning its budget) costs ~no host CPU
_ESCALATE_S = 0.005
_COARSE_SLEEP_S = 0.001


class MonitoredWait:
    """Bounded wait on a step's result arrays.

    `wait(arrays, phase, attempt)` returns normally once the arrays are
    ready (or immediately when the watchdog is disarmed — the caller
    then blocks on the host transfer exactly as before PR 7); raises
    `StepHang` when the budget elapses first. An armed chaos "hang"
    injector for `op=f"serve.{phase}"` trips the hang path
    deterministically without consuming the budget in real time — each
    ladder rung re-polls, so `times=N` hangs exactly N attempts. A
    "stall" injector is the wall-clock variant: it sleeps the REAL
    budget before the StepHang, so the telemetry server's /healthz can
    observe the wedge (tools/chaos.py `telemetry` scenario).
    """

    def __init__(self, budget_s=None):
        self._budget_s = budget_s

    @property
    def armed(self):
        return (self._budget_s if self._budget_s is not None
                else watchdog_budget_s()) is not None

    def wait(self, arrays, phase, attempt=1):
        from ..ops import guardian
        budget = (self._budget_s if self._budget_s is not None
                  else watchdog_budget_s())
        if guardian.faults_armed():
            kind = guardian.poll_fault(f"serve.{phase}",
                                       ("hang", "stall"))
            if kind == "stall":
                # the wall-clock hang variant: burn the REAL budget
                # before the StepHang so the liveness plane (/healthz,
                # profiler/telemetry_server.py) observes a genuinely
                # wedged step — with the watchdog disarmed, model a
                # slow-but-alive step and return normally
                time.sleep(budget if budget is not None
                           else _ESCALATE_S * 10)
                if budget is None:
                    return
                raise StepHang(phase, budget * 1e3, attempt)
            if kind is not None:
                raise StepHang(phase, (budget or 0) * 1e3, attempt)
        if budget is None:
            return
        start = time.perf_counter()
        deadline = start + budget
        escalate = start + min(_ESCALATE_S, budget / 2)
        for a in arrays:
            ready = getattr(a, "is_ready", None)
            if ready is None:
                continue
            while not ready():
                now = time.perf_counter()
                if now >= deadline:
                    raise StepHang(phase, budget * 1e3, attempt)
                # yield, don't spin: XLA's compute threads need the core
                time.sleep(0 if now < escalate else _COARSE_SLEEP_S)


# ---------------------------------------------------------------------------
# crash-resume snapshots
# ---------------------------------------------------------------------------

def request_payload(req, now_ns=None):
    """A Request's resumable identity as a JSON-able dict. Captures WHAT
    was asked and what has been emitted — never device state: the KV
    pool re-prefills on resume via the engine's normal (re-)admission
    path, token-identically. Deadlines serialize as REMAINING seconds
    (the monotonic clock does not survive the process)."""
    return {
        "rid": req.rid,
        "prompt": list(req.prompt),
        "max_new_tokens": req.max_new_tokens,
        "eos_token_id": req.eos_token_id,
        "generated": list(req.generated),
        "arrival_seq": req.arrival_seq,
        "preemptions": req.preemptions,
        "ttl_remaining_s": req.ttl_remaining_s(now_ns),
        # multi-tenant identity (PR 17): which adapter the stream
        # decodes under — restore refuses (adapter_mismatch) when the
        # restoring engine does not have it registered
        "adapter": req.adapter,
        # sampler identity (PR 18): the resolved sampler config,
        # including the resolved seed — (seed, prompt, sampler) is the
        # reproducibility contract, so the restored stream continues
        # byte-identically from the same fold_in positions
        "temperature": req.temperature,
        "top_k": req.top_k,
        "top_p": req.top_p,
        "repetition_penalty": req.repetition_penalty,
        "seed": req.seed,
    }


def payload_request(payload, on_token=None):
    """Rebuild a QUEUED Request from `request_payload` output. The
    emitted-so-far tokens ride in `generated`, so the first admission
    re-prefills prompt + generated and continues the stream exactly
    where the dead process stopped. `on_token` callbacks do not
    serialize — the restoring caller re-attaches its own."""
    ttl = payload.get("ttl_remaining_s")
    req = Request(payload["rid"], payload["prompt"],
                  payload["max_new_tokens"],
                  eos_token_id=payload.get("eos_token_id"),
                  on_token=on_token,
                  ttl_s=max(0.0, ttl) if ttl is not None else None,
                  adapter=payload.get("adapter"),
                  temperature=payload.get("temperature", 0.0),
                  top_k=payload.get("top_k", 0),
                  top_p=payload.get("top_p", 1.0),
                  repetition_penalty=payload.get(
                      "repetition_penalty", 1.0),
                  seed=payload.get("seed"))
    req.generated = list(payload.get("generated") or [])
    # logprobs for pre-crash tokens died with the process — pad with
    # None so the panels stay index-aligned with `generated`
    req.token_logprobs = [None] * len(req.generated)
    req.alt_ids = [None] * len(req.generated)
    req.alt_logprobs = [None] * len(req.generated)
    req.preemptions = int(payload.get("preemptions") or 0)
    return req
