"""Continuous-batching request scheduler (iteration-level scheduling).

Reference analog: the reference serves through a pool of
`AnalysisPredictor` workers, one request per predictor run — batch
composition is frozen for a request's whole lifetime. This module is the
Orca (OSDI'22) iteration-level design instead: scheduling decisions happen
at TOKEN boundaries, so a request joins the running batch the moment a
slot and enough KV blocks are free, and leaves the moment it finishes —
no head-of-batch stragglers, no padding to the slowest tenant.

Policy (deliberately small and predictable):

  * **FCFS admission** — the waiting queue is ordered by arrival; only
    the head is considered (strict FCFS: no skipping past a big request
    to admit a small one, so no starvation).
  * **Free-block watermark** — a request is admitted only if, after
    taking its prompt's blocks, at least `watermark_blocks` remain free.
    The watermark is the growth reserve: running sequences allocate one
    block every `block_size` tokens, and growth ignores the watermark
    (the reserve exists exactly for it).
  * **Preempt-resume by block-table edit** — when growth finds the pool
    dry, the most recently admitted running request is evicted: its
    blocks return to the pool and the request rejoins the waiting queue
    at its original arrival position (FCFS preserved). Nothing is
    copied; resume re-prefills prompt + tokens generated so far
    (recompute-style preemption, the vLLM default) and continues
    token-identically.

Resilience policy (PR 7, paired with serving/resilience.py):

  * **Deadlines/TTLs** — a request may carry an absolute deadline
    (monotonic ns). The scheduler never decides on wall time itself; it
    exposes the bookkeeping (`Request.expired`, `expired_waiting`) and
    the engine applies it at admission and at every iteration boundary.
  * **Bounded queue** — `max_queue_depth` caps the waiting queue; the
    engine turns a full queue into a structured `ServeRefusal`
    (`queue_full`) instead of queueing work that will expire unserved.
    `estimated_wait_steps` is the admission-time feasibility signal:
    a lower bound on decode steps before a new arrival gets a slot.
  * **Anti-starvation aging guard** — LIFO preemption alone can ping-pong
    one victim forever: a request that keeps being the newest admission
    is evicted every time the pool runs dry and never finishes. A request
    preempted `aging_max_preemptions` times becomes *protected*:
    `preempt_victim` skips protected requests, so its next admission is
    the one that sticks. When every candidate is protected the caller
    must stop evicting (grow fails / self-preempts) rather than starve.

The scheduler is pure host-side bookkeeping over integers — it owns no
device state and is unit-testable without jax. The engine
(serving/engine.py) asks it *who* runs; the block pool (serving/cache.py)
says *where* their KV lives.
"""
from __future__ import annotations

import math
import time

__all__ = ["Request", "Scheduler", "QUEUED", "RUNNING", "FINISHED",
           "FAILED", "CANCELLED", "EXPIRED"]

QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"   # client called cancel(request_id)
EXPIRED = "EXPIRED"       # deadline/TTL passed while queued or running


class Request:
    """One generation request's lifecycle state.

    `generated` accumulates output token ids (streamed through
    `on_token` as they land); `cached_len` is how many tokens of
    prompt+generated currently have KV in the pool (0 after a
    preemption — resume re-prefills). `blocks` is the request's block
    table: the ONLY thing admission/eviction edits.
    """

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "on_token", "state", "generated", "blocks", "slot",
                 "cached_len", "arrival_seq", "admit_seq", "preemptions",
                 "error", "enqueue_ns", "first_token_ns", "finish_ns",
                 "deadline_ns", "cancel_requested", "admit_ns",
                 "last_token_ns", "token_ns", "adapter", "prefix_hit",
                 "chew", "temperature", "top_k", "top_p",
                 "repetition_penalty", "seed", "token_logprobs",
                 "alt_ids", "alt_logprobs")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id=None,
                 on_token=None, ttl_s=None, adapter=None,
                 temperature=0.0, top_k=0, top_p=1.0,
                 repetition_penalty=1.0, seed=None):
        self.rid = rid
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.on_token = on_token
        self.state = QUEUED
        self.generated = []
        self.blocks = []
        self.slot = None
        self.cached_len = 0
        self.arrival_seq = None
        self.admit_seq = None
        self.preemptions = 0
        self.error = None
        self.enqueue_ns = time.perf_counter_ns()
        self.first_token_ns = None
        self.finish_ns = None
        # latency accounting (PR 12): first admission time (queue wait =
        # admit_ns - enqueue_ns) and per-token emission timestamps
        # (bounded by max_new_tokens) so a completed handle can report
        # its own TTFT / inter-token percentiles
        self.admit_ns = None
        self.last_token_ns = None
        self.token_ns = []
        # absolute deadline on the perf_counter_ns clock (None = no TTL);
        # checked by the ENGINE at admission and at iteration boundaries
        self.deadline_ns = (None if ttl_s is None
                            else self.enqueue_ns + int(ttl_s * 1e9))
        # set by engine.cancel(): honored immediately when the engine is
        # between steps, or by the next boundary sweep when the cancel
        # arrives from inside a streaming callback mid-step — the fixed
        # slot layout is only ever edited between decode steps
        self.cancel_requested = False
        # multi-tenant serving (PR 17, serving/tenancy.py): the named
        # LoRA-style adapter this stream decodes under (None = base
        # weights); `prefix_hit` is the shared-prefix token count the
        # LAST admission aliased from the prefix cache (0 = cold), and
        # `chew` holds the un-prefilled suffix tokens a prefix-hit
        # admission still has to feed through the decode step one per
        # iteration before real sampling resumes
        self.adapter = adapter
        self.prefix_hit = 0
        self.chew = []
        # compiled stochastic sampling (PR 18, serving/sampling.py):
        # per-request sampler config — VALUES in the one compiled decode
        # (temperature=0 is greedy under the same program). `seed` is
        # resolved by the engine (crc32(rid) default) and serializes, so
        # the stream replays byte-identically across preempt/resume,
        # watchdog rebuild, and crash resume. `token_logprobs` parallels
        # `generated` (None for tokens re-fed from chew/prefix, whose
        # logprob was never an output of the step that emitted them);
        # `alt_ids`/`alt_logprobs` hold the optional static-K top-k
        # alternative panels when the engine enables logprobs_topk.
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.repetition_penalty = float(repetition_penalty)
        self.seed = seed
        self.token_logprobs = []
        self.alt_ids = []
        self.alt_logprobs = []

    @property
    def context_len(self):
        """Tokens the model has consumed/produced so far (prompt +
        generated) — what a resume must re-prefill."""
        return len(self.prompt) + len(self.generated)

    @property
    def remaining_tokens(self):
        """Decode steps this request still wants (upper bound: eos may
        stop it earlier)."""
        return max(0, self.max_new_tokens - len(self.generated))

    @property
    def finished(self):
        return self.state in (FINISHED, FAILED, CANCELLED, EXPIRED)

    def expired(self, now_ns=None):
        """Deadline passed (False when the request carries no TTL)."""
        if self.deadline_ns is None:
            return False
        if now_ns is None:
            now_ns = time.perf_counter_ns()
        return now_ns >= self.deadline_ns

    def latency(self):
        """Per-request latency summary off the emission timestamps:
        TTFT (enqueue -> first token), queue wait (enqueue -> first
        admission), and inter-token p50/p99 over this request's own
        token stream. Valid any time; most useful on a completed
        handle. Times in milliseconds; None where not yet observed."""
        out = {
            "ttft_ms": (None if self.first_token_ns is None
                        else (self.first_token_ns - self.enqueue_ns)
                        / 1e6),
            "queue_wait_ms": (None if self.admit_ns is None
                              else (self.admit_ns - self.enqueue_ns)
                              / 1e6),
            "tokens": len(self.generated),
            "inter_token_p50_ms": None,
            "inter_token_p99_ms": None,
        }
        if len(self.token_ns) >= 2:
            gaps = sorted((b - a) / 1e6 for a, b in
                          zip(self.token_ns, self.token_ns[1:]))
            out["inter_token_p50_ms"] = gaps[len(gaps) // 2]
            out["inter_token_p99_ms"] = gaps[
                min(len(gaps) - 1, int(0.99 * len(gaps)))]
        return out

    def logprobs(self):
        """Per-token logprob summary, `latency()`-style: the sampled
        token's logprob under the RAW (pre-masking) distribution for each
        generated token, plus the optional top-k alternative panels when
        the engine was built with ``logprobs_topk > 0``. Entries are None
        for tokens re-fed from a prefix hit or crash resume (their
        emitting step's outputs no longer exist). Valid any time —
        streaming callbacks may read the live handle mid-flight."""
        return {
            "token_logprobs": list(self.token_logprobs),
            "topk_ids": [None if a is None else list(a)
                         for a in self.alt_ids],
            "topk_logprobs": [None if a is None else list(a)
                              for a in self.alt_logprobs],
        }

    def ttl_remaining_s(self, now_ns=None):
        """Seconds until the deadline (None without one; may be <= 0).
        Serialized into crash-resume snapshots so a restored request
        re-arms RELATIVE time — the monotonic clock does not survive a
        process."""
        if self.deadline_ns is None:
            return None
        if now_ns is None:
            now_ns = time.perf_counter_ns()
        return (self.deadline_ns - now_ns) / 1e9


class Scheduler:
    """FCFS + watermark admission + preempt-resume over `allocator`."""

    def __init__(self, num_slots, allocator, block_size,
                 watermark_blocks=None, max_queue_depth=None,
                 aging_max_preemptions=3):
        self.num_slots = int(num_slots)
        self.allocator = allocator
        self.block_size = int(block_size)
        if watermark_blocks is None:
            # default growth reserve: one block per slot, bounded by 5%
            # of the pool — enough that a full batch can each cross a
            # block boundary once without an eviction storm
            watermark_blocks = min(self.num_slots,
                                   max(1, allocator.capacity // 20))
        self.watermark_blocks = int(watermark_blocks)
        # bounded-queue backpressure: None = unbounded (library default;
        # a production deployment should size this against its SLO)
        self.max_queue_depth = (None if max_queue_depth is None
                                else int(max_queue_depth))
        # aging guard: preemptions a request absorbs before it becomes
        # protected from further eviction (see preempt_victim)
        self.aging_max_preemptions = int(aging_max_preemptions)
        self.waiting = []            # Requests, ordered by arrival_seq
        self.running = []            # admission order
        self.slots = [None] * self.num_slots
        self._arrivals = 0
        self._admissions = 0

    # -- sizing -------------------------------------------------------------
    def blocks_needed(self, num_tokens):
        """Blocks for `num_tokens` cached tokens plus the next write."""
        return max(1, math.ceil((num_tokens + 1) / self.block_size))

    def max_blocks_of(self, req):
        """Blocks the request needs at its longest (prompt fully decoded:
        the final generated token is returned but never written)."""
        peak = len(req.prompt) + req.max_new_tokens - 1
        return self.blocks_needed(peak)

    def block_budget(self):
        """Blocks a single request may ever hold: pool capacity minus the
        admission watermark (try_admit never hands out the reserve, so a
        request needing more than this could wait forever)."""
        return self.allocator.capacity - self.watermark_blocks

    def can_ever_fit(self, req, shared_blocks=0):
        """False when no amount of waiting/eviction can serve this
        request — its peak block need exceeds what admission will ever
        grant (capacity minus the watermark reserve). Refuse such a
        request at enqueue: strict FCFS would deadlock the whole queue
        behind it.

        `shared_blocks` is the prefix-cache aliasing credit (PR 17):
        blocks the request would inherit by reference rather than
        allocate. The pre-aliasing math assumed exclusive ownership and
        would spuriously refuse a multi-tenant request whose private
        footprint fits fine once its shared system prompt is counted
        once — refcounted blocks cost the pool nothing extra."""
        return self.max_blocks_of(req) - int(shared_blocks) \
            <= self.block_budget()

    def queue_full(self):
        """The bounded waiting queue is at capacity (engine refuses with
        `queue_full` instead of enqueueing)."""
        return self.max_queue_depth is not None \
            and len(self.waiting) >= self.max_queue_depth

    def estimated_wait_steps(self, req=None):
        """Lower bound on decode steps before a NEW arrival gets a slot:
        every token still owed to requests ahead of it (running + the
        whole waiting queue), served `num_slots` at a time. Deliberately
        optimistic — it ignores preemption re-prefills and eos early
        stops cut it the other way — so a refusal on this bound
        (`deadline_infeasible`) is never pessimistic guessing."""
        ahead = sum(r.remaining_tokens for r in self.running) \
            + sum(r.remaining_tokens for r in self.waiting if r is not req)
        return math.ceil(ahead / max(1, self.num_slots))

    # -- queue --------------------------------------------------------------
    def enqueue(self, req):
        req.arrival_seq = self._arrivals
        self._arrivals += 1
        self.waiting.append(req)

    def remove_waiting(self, req):
        """Drop a queued request (cancel/expiry); no-op when absent."""
        try:
            self.waiting.remove(req)
        except ValueError:
            pass

    def expired_waiting(self, now_ns=None):
        """Queued requests whose deadline has passed (engine clears them
        at the iteration boundary before admission looks at the head —
        an expired head must never block FCFS admission of live work)."""
        if now_ns is None:
            now_ns = time.perf_counter_ns()
        return [r for r in self.waiting if r.expired(now_ns)]

    def _requeue(self, req):
        """Re-insert a preempted request by ORIGINAL arrival order."""
        req.state = QUEUED
        i = 0
        while i < len(self.waiting) \
                and self.waiting[i].arrival_seq < req.arrival_seq:
            i += 1
        self.waiting.insert(i, req)

    # -- admission ----------------------------------------------------------
    def try_admit(self, prefix_hook=None):
        """Admit the FCFS head if a slot is free and its context's blocks
        leave the watermark intact. Returns the Request (now RUNNING,
        blocks + slot assigned, KV not yet filled) or None.

        `prefix_hook(req) -> (shared_blocks, hit_tokens)` is the PR 17
        shared-prefix probe: it ACQUIRES (increfs) the longest cached
        block run matching the head's context, so admission only
        allocates the private remainder and the watermark check counts
        each refcounted block once. When admission then fails anyway
        (watermark / slot pressure) the acquired references are dropped
        symmetrically — the hook's incref and this free are the only
        two sides of the claim."""
        if not self.waiting:
            return None
        try:
            slot = self.slots.index(None)
        except ValueError:
            return None
        req = self.waiting[0]
        shared, hit = [], 0
        if prefix_hook is not None:
            shared, hit = prefix_hook(req)
        needed = max(0, self.blocks_needed(req.context_len) - len(shared))
        if self.allocator.num_free - needed < self.watermark_blocks:
            if shared:
                self.allocator.free(shared)     # undo the hook's claim
            return None
        blocks = self.allocator.allocate(needed)
        if blocks is None:
            if shared:
                self.allocator.free(shared)
            return None
        self.waiting.pop(0)
        req.blocks = list(shared) + blocks
        req.prefix_hit = hit
        req.slot = slot
        req.state = RUNNING
        req.admit_seq = self._admissions
        self._admissions += 1
        self.slots[slot] = req
        self.running.append(req)
        return req

    # -- growth / preemption ------------------------------------------------
    def grow(self, req):
        """Allocate one more block for `req`. Growth may dip into the
        watermark reserve — that is what it is for."""
        got = self.allocator.allocate(1)
        if got is None:
            return False
        req.blocks.extend(got)
        return True

    def protected(self, req):
        """The aging guard: a request preempted `aging_max_preemptions`
        times has paid its dues — it is never chosen as a victim again,
        so sustained LIFO preemption cannot starve it forever."""
        return req.preemptions >= self.aging_max_preemptions

    def preempt_victim(self, exclude=None):
        """The most recently admitted running request other than
        `exclude` (LIFO eviction: the newest tenant re-prefills, the
        oldest keeps its progress). Requests past the aging guard are
        skipped; when every candidate is protected this returns None and
        the caller must stop evicting (fail or self-preempt the grower)
        rather than override the guard."""
        cands = [r for r in self.running
                 if r is not exclude and not self.protected(r)]
        return max(cands, key=lambda r: r.admit_seq) if cands else None

    def preempt(self, req):
        """Evict: blocks back to the pool (a DECREF — shared prefix
        blocks survive for their other owners), KV forgotten
        (cached_len=0 — resume re-prefills context_len tokens), request
        back in the waiting queue at its arrival position."""
        self._detach(req)
        req.preemptions += 1
        req.cached_len = 0
        req.prefix_hit = 0
        req.chew = []
        self._requeue(req)

    def release(self, req):
        """A finished/failed request leaves the batch."""
        self._detach(req)

    def _detach(self, req):
        if req.blocks:
            self.allocator.free(req.blocks)
            req.blocks = []
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
        if req in self.running:
            self.running.remove(req)

    # -- introspection ------------------------------------------------------
    @property
    def demand(self):
        """Requests that want a slot right now."""
        return len(self.running) + len(self.waiting)

    def info(self):
        return {
            "waiting": len(self.waiting),
            "running": len(self.running),
            "free_blocks": self.allocator.num_free,
            "shared_blocks": getattr(self.allocator, "num_shared", 0),
            "watermark_blocks": self.watermark_blocks,
            "max_queue_depth": self.max_queue_depth,
            "aging_max_preemptions": self.aging_max_preemptions,
            "slots": [r.rid if r is not None else None
                      for r in self.slots],
        }
