"""Paged KV cache: block-pool attention memory for continuous batching.

Reference analog: the reference serves through `fused_multi_transformer`'s
dense per-request `[B, max_len, H, D]` cache buffers behind
`AnalysisPredictor` (inference/api/analysis_predictor.h:95). Dense buffers
reserve `max_len` for EVERY sequence, so a 16-token chat and a 2k-token
document cost the same HBM and a new request of a different length means a
new buffer (and on TPU a new compiled shape). This module is the
PagedAttention memory model (vLLM, SOSP'23) rebuilt TPU-native:

  * ONE preallocated block pool per layer, shape
    ``[num_blocks, block_size, H, D]`` — total KV memory is fixed at
    engine construction, independent of how many sequences share it;
  * each sequence owns an ordered list of block ids (its *block table*);
    token position ``p`` of a sequence lives at
    ``(table[p // block_size], p % block_size)``;
  * admission / growth / eviction / preemption are *host-side edits of
    integer tables* — no cache copy, no reshape, no recompile. The
    compiled decode step (serving/engine.py) only ever sees the fixed
    ``[S, max_blocks]`` int32 table and the fixed pools, so sequences of
    wildly different lengths batch into one executable with zero
    retraces.

Block 0 is reserved as the *null block*: inactive batch slots and padded
table entries point at it, so in-graph gathers/scatters never need a
branch — garbage goes to (and comes from) block 0 and is masked out of
the attention softmax.

The device side of the design lives in
`nn/functional/attention.py::paged_decode_attention` (gather-by-block-table
attention) and `scatter_prefill` below (bulk prompt-KV insertion); the
policy side (who gets blocks, who is evicted) lives in
serving/scheduler.py.
"""
from __future__ import annotations

from collections import deque

import jax.numpy as jnp

__all__ = ["BlockAllocator", "PagedKVCache", "PagedCacheView",
           "scatter_prefill", "NULL_BLOCK", "pool_bytes_per_block",
           "num_blocks_for_bytes"]

# block id 0 is never allocated: it is the write/read target for inactive
# slots and out-of-range table entries (see module docstring)
NULL_BLOCK = 0


class BlockAllocator:
    """Host-side refcounted free-list allocator over the pool's block ids.

    Pure bookkeeping — no device state. O(1) allocate/free; the free
    count is the scheduler's admission-watermark signal.

    PR 17 makes ownership refcounted for shared-prefix KV reuse: a block
    aliased into several sequences' tables (serving/tenancy.py
    PrefixCache) carries one reference per owner, `free` is a decref
    that returns the block to the free list only at zero, and the free
    list holds exactly the refcount-zero blocks — so `num_free` counts
    every shared block ONCE by construction and the watermark/admission
    math needs no aliasing-aware correction. Exclusive ownership (every
    pre-PR 17 caller) behaves exactly as before: allocate hands out a
    block at refcount 1 and the first free releases it.
    """

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (one is the reserved null block), got "
                f"{num_blocks}")
        self.num_blocks = int(num_blocks)
        # block 0 reserved; 1..num_blocks-1 allocatable
        self._free = deque(range(1, self.num_blocks))
        self._refs = {}          # block id -> refcount (allocated only)

    @property
    def num_free(self):
        return len(self._free)

    @property
    def capacity(self):
        """Allocatable blocks (pool minus the null block)."""
        return self.num_blocks - 1

    @property
    def num_shared(self):
        """Allocated blocks with more than one owner (prefix aliases)."""
        return sum(1 for rc in self._refs.values() if rc > 1)

    def refcount(self, block):
        """Live owners of `block` (0 when free/never allocated) — the
        engine's copy-on-write trigger reads this before every write
        that would land in a possibly-shared block."""
        return self._refs.get(block, 0)

    def allocate(self, n):
        """Pop `n` block ids (each at refcount 1), or None (allocating
        nothing) when fewer than `n` are free — admission is
        all-or-nothing."""
        if n > len(self._free):
            return None
        out = [self._free.popleft() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def incref(self, block):
        """Add an owner to an ALLOCATED block (prefix-cache aliasing:
        a new sequence's table points at an existing block's KV)."""
        if block == NULL_BLOCK:
            raise ValueError("attempt to share the reserved null block")
        rc = self._refs.get(block)
        if rc is None:
            raise ValueError(
                f"incref of free/unallocated block {block}")
        self._refs[block] = rc + 1

    def free(self, blocks):
        """Drop one owner per listed block; a block rejoins the free
        list only when its LAST owner lets go (shared prefix blocks
        survive any one sequence's eviction)."""
        for b in blocks:
            if b == NULL_BLOCK:
                raise ValueError("attempt to free the reserved null block")
            rc = self._refs.get(b)
            if rc is None:
                raise ValueError(f"free of unallocated block {b}")
            if rc == 1:
                del self._refs[b]
                self._free.append(b)
            else:
                self._refs[b] = rc - 1


class PagedCacheView:
    """One layer's paged cache as seen from INSIDE the compiled decode
    step: the layer's pools plus the batch's block tables / lengths /
    active mask (jnp arrays or tracers). `GPTAttention` detects this view
    by its `block_tables` attribute and routes to the paged decode path;
    `updated()` threads the written pools back out of the model.

    int8 mode carries the per-block-per-head scale side-tables
    (`k_scales`/`v_scales`, quantization/kv_cache.py); `kernel` pins the
    attention variant the owning engine resolved at construction
    (nn/functional/attention.resolve_paged_kernel), so a mid-run flag
    flip never re-keys a live engine's compiled decode step."""

    __slots__ = ("k_pool", "v_pool", "block_tables", "seq_lens", "active",
                 "block_size", "k_scales", "v_scales", "kernel")

    def __init__(self, k_pool, v_pool, block_tables, seq_lens, active,
                 block_size, k_scales=None, v_scales=None, kernel=None):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.block_tables = block_tables
        self.seq_lens = seq_lens
        self.active = active
        self.block_size = int(block_size)
        self.k_scales = k_scales
        self.v_scales = v_scales
        self.kernel = kernel

    def updated(self, k_pool, v_pool, k_scales=None, v_scales=None):
        return PagedCacheView(k_pool, v_pool, self.block_tables,
                              self.seq_lens, self.active, self.block_size,
                              k_scales=k_scales, v_scales=v_scales,
                              kernel=self.kernel)


def _is_int8(dtype):
    return dtype in ("int8", jnp.int8) or jnp.dtype(dtype) == jnp.int8


class PagedKVCache:
    """The device pools + the allocator, sized once at engine start.

    Pools are stacked over layers — ``[L, num_blocks, block_size, H, D]``
    — so the compiled decode/prefill programs donate exactly two buffers
    regardless of depth. Sizing policy (blocks per context length, the
    admission budget) lives in ONE place: serving/scheduler.py.

    ``dtype=jnp.int8`` turns on the quantized KV mode
    (quantization/kv_cache.py): int8 pools plus fp32 per-block-per-head
    scale side-tables ``[L, num_blocks, H]`` (`k_scales`/`v_scales`) —
    each cached token costs 1 byte per element instead of 4, so the same
    HBM watermark admits ~2x the streams before `kv_exhausted`.
    """

    def __init__(self, num_layers, num_heads, head_dim, num_blocks,
                 block_size, dtype=jnp.float32):
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.quantized = _is_int8(dtype)
        self.dtype = jnp.int8 if self.quantized else dtype
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k_pools = jnp.zeros(shape, self.dtype)
        self.v_pools = jnp.zeros(shape, self.dtype)
        if self.quantized:
            sshape = (self.num_layers, self.num_blocks, self.num_heads)
            self.k_scales = jnp.zeros(sshape, jnp.float32)
            self.v_scales = jnp.zeros(sshape, jnp.float32)
        else:
            self.k_scales = None
            self.v_scales = None
        self.allocator = BlockAllocator(self.num_blocks)


def pool_bytes_per_block(num_layers, num_heads, head_dim, block_size,
                         dtype=jnp.float32):
    """Device bytes ONE pool block costs across k+v (and the int8 scale
    side-tables) over every layer — the unit of the serving capacity
    math: `pool bytes = num_blocks * pool_bytes_per_block(...)`."""
    if _is_int8(dtype):
        payload = block_size * num_heads * head_dim        # 1 byte/elem
        scales = num_heads * 4
        return 2 * num_layers * (payload + scales)
    itemsize = jnp.dtype(dtype).itemsize
    return 2 * num_layers * block_size * num_heads * head_dim * itemsize


def num_blocks_for_bytes(budget_bytes, num_layers, num_heads, head_dim,
                         block_size, dtype=jnp.float32):
    """Blocks a byte budget buys (>= 2: the null block + one real one).
    The int8 capacity win reads directly off this: the same budget buys
    ~4x the fp32 blocks (~2x bf16), so the watermark admits ~2-4x the
    concurrent streams before `kv_exhausted` refusals begin."""
    per = pool_bytes_per_block(num_layers, num_heads, head_dim,
                               block_size, dtype)
    return max(2, int(budget_bytes) // per)


def scatter_prefill(k_pools, v_pools, k_layers, v_layers, block_row,
                    length, block_size, k_scales=None, v_scales=None):
    """Bulk-insert a prefilled prompt's K/V into the pools.

    k_layers/v_layers: ``[L, T_bucket, H, D]`` — the per-layer prompt KV
    computed by the bucketed prefill program (right-padded to the bucket).
    block_row: ``[max_blocks]`` int32 — the sequence's block table.
    length: scalar int32 — true prompt length; padded positions are
    routed to the null block (their values are garbage by construction
    and never read: gather masks by `seq_lens`).

    With int8 pools, pass the scale side-tables (``[L, num_blocks, H]``):
    each layer's tokens quantize under freshly computed per-block-per-head
    scales (quantization/kv_cache.py `quantize_scatter`) and the call
    returns ``(k_pools, v_pools, k_scales, v_scales)``.

    Traceable (runs inside the jitted prefill program). Returns the
    updated pools.
    """
    t_bucket = k_layers.shape[1]
    pidx = jnp.arange(t_bucket, dtype=jnp.int32)
    blocks = jnp.where(pidx < length,
                       block_row[pidx // block_size],
                       jnp.asarray(NULL_BLOCK, jnp.int32))
    offs = pidx % block_size
    num_layers = k_layers.shape[0]
    if k_scales is not None:
        from ..quantization.kv_cache import quantize_scatter
        for layer in range(num_layers):
            kp, ks = quantize_scatter(k_pools[layer], k_scales[layer],
                                      k_layers[layer], blocks, offs,
                                      block_row, length)
            vp, vs = quantize_scatter(v_pools[layer], v_scales[layer],
                                      v_layers[layer], blocks, offs,
                                      block_row, length)
            k_pools = k_pools.at[layer].set(kp)
            v_pools = v_pools.at[layer].set(vp)
            k_scales = k_scales.at[layer].set(ks)
            v_scales = v_scales.at[layer].set(vs)
        return k_pools, v_pools, k_scales, v_scales
    for layer in range(num_layers):
        k_pools = k_pools.at[layer, blocks, offs].set(
            k_layers[layer].astype(k_pools.dtype))
        v_pools = v_pools.at[layer, blocks, offs].set(
            v_layers[layer].astype(v_pools.dtype))
    return k_pools, v_pools
