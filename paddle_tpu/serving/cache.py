"""Paged KV cache: block-pool attention memory for continuous batching.

Reference analog: the reference serves through `fused_multi_transformer`'s
dense per-request `[B, max_len, H, D]` cache buffers behind
`AnalysisPredictor` (inference/api/analysis_predictor.h:95). Dense buffers
reserve `max_len` for EVERY sequence, so a 16-token chat and a 2k-token
document cost the same HBM and a new request of a different length means a
new buffer (and on TPU a new compiled shape). This module is the
PagedAttention memory model (vLLM, SOSP'23) rebuilt TPU-native:

  * ONE preallocated block pool per layer, shape
    ``[num_blocks, block_size, H, D]`` — total KV memory is fixed at
    engine construction, independent of how many sequences share it;
  * each sequence owns an ordered list of block ids (its *block table*);
    token position ``p`` of a sequence lives at
    ``(table[p // block_size], p % block_size)``;
  * admission / growth / eviction / preemption are *host-side edits of
    integer tables* — no cache copy, no reshape, no recompile. The
    compiled decode step (serving/engine.py) only ever sees the fixed
    ``[S, max_blocks]`` int32 table and the fixed pools, so sequences of
    wildly different lengths batch into one executable with zero
    retraces.

Block 0 is reserved as the *null block*: inactive batch slots and padded
table entries point at it, so in-graph gathers/scatters never need a
branch — garbage goes to (and comes from) block 0 and is masked out of
the attention softmax.

The device side of the design lives in
`nn/functional/attention.py::paged_decode_attention` (gather-by-block-table
attention) and `scatter_prefill` below (bulk prompt-KV insertion); the
policy side (who gets blocks, who is evicted) lives in
serving/scheduler.py.
"""
from __future__ import annotations

from collections import deque

import jax.numpy as jnp

__all__ = ["BlockAllocator", "PagedKVCache", "PagedCacheView",
           "scatter_prefill", "NULL_BLOCK"]

# block id 0 is never allocated: it is the write/read target for inactive
# slots and out-of-range table entries (see module docstring)
NULL_BLOCK = 0


class BlockAllocator:
    """Host-side free-list allocator over the pool's block ids.

    Pure bookkeeping — no device state. O(1) allocate/free; the free
    count is the scheduler's admission-watermark signal.
    """

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (one is the reserved null block), got "
                f"{num_blocks}")
        self.num_blocks = int(num_blocks)
        # block 0 reserved; 1..num_blocks-1 allocatable
        self._free = deque(range(1, self.num_blocks))

    @property
    def num_free(self):
        return len(self._free)

    @property
    def capacity(self):
        """Allocatable blocks (pool minus the null block)."""
        return self.num_blocks - 1

    def allocate(self, n):
        """Pop `n` block ids, or None (allocating nothing) when fewer
        than `n` are free — admission is all-or-nothing."""
        if n > len(self._free):
            return None
        return [self._free.popleft() for _ in range(n)]

    def free(self, blocks):
        for b in blocks:
            if b == NULL_BLOCK:
                raise ValueError("attempt to free the reserved null block")
            self._free.append(b)


class PagedCacheView:
    """One layer's paged cache as seen from INSIDE the compiled decode
    step: the layer's pools plus the batch's block tables / lengths /
    active mask (jnp arrays or tracers). `GPTAttention` detects this view
    by its `block_tables` attribute and routes to the paged decode path;
    `updated()` threads the written pools back out of the model."""

    __slots__ = ("k_pool", "v_pool", "block_tables", "seq_lens", "active",
                 "block_size")

    def __init__(self, k_pool, v_pool, block_tables, seq_lens, active,
                 block_size):
        self.k_pool = k_pool
        self.v_pool = v_pool
        self.block_tables = block_tables
        self.seq_lens = seq_lens
        self.active = active
        self.block_size = int(block_size)

    def updated(self, k_pool, v_pool):
        return PagedCacheView(k_pool, v_pool, self.block_tables,
                              self.seq_lens, self.active, self.block_size)


class PagedKVCache:
    """The device pools + the allocator, sized once at engine start.

    Pools are stacked over layers — ``[L, num_blocks, block_size, H, D]``
    — so the compiled decode/prefill programs donate exactly two buffers
    regardless of depth. Sizing policy (blocks per context length, the
    admission budget) lives in ONE place: serving/scheduler.py.
    """

    def __init__(self, num_layers, num_heads, head_dim, num_blocks,
                 block_size, dtype=jnp.float32):
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.dtype = dtype
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k_pools = jnp.zeros(shape, dtype)
        self.v_pools = jnp.zeros(shape, dtype)
        self.allocator = BlockAllocator(self.num_blocks)


def scatter_prefill(k_pools, v_pools, k_layers, v_layers, block_row,
                    length, block_size):
    """Bulk-insert a prefilled prompt's K/V into the pools.

    k_layers/v_layers: ``[L, T_bucket, H, D]`` — the per-layer prompt KV
    computed by the bucketed prefill program (right-padded to the bucket).
    block_row: ``[max_blocks]`` int32 — the sequence's block table.
    length: scalar int32 — true prompt length; padded positions are
    routed to the null block (their values are garbage by construction
    and never read: gather masks by `seq_lens`).

    Traceable (runs inside the jitted prefill program). Returns the
    updated pools.
    """
    t_bucket = k_layers.shape[1]
    pidx = jnp.arange(t_bucket, dtype=jnp.int32)
    blocks = jnp.where(pidx < length,
                       block_row[pidx // block_size],
                       jnp.asarray(NULL_BLOCK, jnp.int32))
    offs = pidx % block_size
    num_layers = k_layers.shape[0]
    for layer in range(num_layers):
        k_pools = k_pools.at[layer, blocks, offs].set(
            k_layers[layer].astype(k_pools.dtype))
        v_pools = v_pools.at[layer, blocks, offs].set(
            v_layers[layer].astype(v_pools.dtype))
    return k_pools, v_pools
