"""Serving: continuous batching + paged KV cache + compiled decode.

The millions-of-users path of the north star (ROADMAP item 2), replacing
the reference's one-request-per-`AnalysisPredictor` serving model
(inference/api/analysis_predictor.h:95) with:

  * `LLMEngine`     — multi-tenant engine: ONE compiled decode-step
                      executable (fixed slot layout, donated pools, zero
                      retraces under stream churn), bucketed prefill,
                      streaming token callbacks (serving/engine.py);
  * `Scheduler`     — iteration-level (Orca-style) FCFS scheduling with
                      free-block watermark admission and preempt-resume
                      via block-table edits (serving/scheduler.py);
  * `PagedKVCache`  — the vLLM/PagedAttention block-pool memory model,
                      TPU-native (serving/cache.py), paired with
                      `nn.functional.paged_decode_attention`;
  * resilience      — deadlines/TTLs + `cancel()`, bounded-queue
                      backpressure (`ServeRefusal`), hung-step watchdog
                      (`FLAGS_serve_step_timeout_ms` + recovery ladder),
                      eager-fallback degraded mode, and crash-resumable
                      serving state (serving/resilience.py +
                      `incubate.checkpoint.ServeCheckpointer`).

Quick start::

    from paddle_tpu.serving import LLMEngine
    engine = LLMEngine(model, max_batch_size=8, block_size=16)
    outs = engine.generate([[5, 3, 9], [7, 1]], max_new_tokens=32)

Telemetry: `serve.*` events in the fusion flight recorder
(`FLAGS_profiler_events`), `engine.stats()`, `tools/serve_bench.py`, and
the `fusion_doctor` serving section.
"""
from __future__ import annotations

from .cache import (BlockAllocator, PagedKVCache, PagedCacheView,  # noqa: F401
                    scatter_prefill, NULL_BLOCK, pool_bytes_per_block,
                    num_blocks_for_bytes)
from .scheduler import (Request, Scheduler, QUEUED, RUNNING,  # noqa: F401
                        FINISHED, FAILED, CANCELLED, EXPIRED)
from .resilience import ServeRefusal, StepHang  # noqa: F401
from .tenancy import PrefixCache, AdapterSet  # noqa: F401
from .engine import LLMEngine, ServeStats  # noqa: F401

__all__ = ["LLMEngine", "ServeStats", "Request", "Scheduler",
           "PagedKVCache", "PagedCacheView", "BlockAllocator",
           "scatter_prefill", "NULL_BLOCK", "QUEUED", "RUNNING",
           "FINISHED", "FAILED", "CANCELLED", "EXPIRED",
           "ServeRefusal", "StepHang", "pool_bytes_per_block",
           "num_blocks_for_bytes", "PrefixCache", "AdapterSet"]
