"""Compiled stochastic sampling: the per-slot sampler head of the ONE decode.

Reference analog: the reference samples on the host — `paddle.tensor.search`
top-k/top-p kernels invoked per step from the python generation loop
(generation_utils.py), with a host round-trip between logits and the next
token. Every sampler-config change there recompiles nothing because nothing
is compiled; here EVERYTHING is compiled, so the sampler must be a *value*
program, not a *structure* program:

  * per-slot temperature / top-k / top-p / repetition-penalty / seed live in
    fixed ``[max_batch]`` buffers, edited like tokens/lens on join/leave —
    never reshaping, never retracing. Greedy is temperature=0 under the SAME
    executable; a batch may mix greedy and five different sampler configs
    and decode still compiles exactly once;
  * per-slot keys are ``fold_in(PRNGKey(seed), position)`` stream positions
    derived in-graph (framework/random.py::slot_sample_keys), where
    ``position`` is the count of known context tokens at sampling time.
    Replays — preemption re-prefill, watchdog rung-2 rebuild, kill-9
    resume — restore the same positions, so a given (seed, prompt, sampler
    config) reproduces its token stream byte-identically;
  * the whole stochastic path sits under one ``lax.cond`` on
    ``any(temperature > 0)``: an all-greedy batch never executes a sort.

Masking order follows the de-facto contract (HF logits processors):
repetition penalty -> temperature -> top-k -> top-p, then Gumbel-max
(``jax.random.categorical``) over the surviving logits. ``top_k=0`` and
``top_p>=1`` are exact no-ops, and every per-slot config with
``temperature=0`` returns ``argmax`` of the RAW logits — bit-identical to
the greedy-only decode this module replaces.

Logprobs ride the same program: the chosen-token logprob (from the raw,
pre-masking distribution) and an optional static-K panel of top-k
alternatives are extra value outputs — zero additional compiles.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.random import slot_sample_keys

__all__ = ["SAMPLER_VERSION", "validate_sampler", "default_seed",
           "apply_repetition_penalty", "apply_temperature", "apply_top_k",
           "apply_top_p", "sample_tokens"]

# Keyed into the AOT decode digest: any change to the sampling math below
# must bump this so stale exported executables are refused, not replayed.
# v2: top-k and top-p share one descending sort (XLA CPU sorts dominate the
# head's cost; summation order inside the shared softmax shifts borderline
# nucleus ties, so old exports must not replay).
SAMPLER_VERSION = 2

_NEG_INF = -1e30


def default_seed(request_id):
    """Process-stable default seed for a request: crc32 of the request id.
    The rid serializes through crash checkpoints, so a resumed request that
    never chose a seed still replays the same stream."""
    import zlib
    return zlib.crc32(str(request_id).encode("utf-8")) & 0xFFFFFFFF


def validate_sampler(temperature, top_k, top_p, repetition_penalty):
    """Raise ValueError (engine surfaces it as a `sampler_mismatch` refusal)
    for parameter values outside the compiled program's contract."""
    t = float(temperature)
    if not (t >= 0.0) or t != t or t == float("inf"):
        raise ValueError(f"temperature must be finite and >= 0, got {temperature}")
    if int(top_k) < 0:
        raise ValueError(f"top_k must be >= 0 (0 disables), got {top_k}")
    p = float(top_p)
    if not (0.0 < p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    r = float(repetition_penalty)
    if not (r > 0.0) or r == float("inf"):
        raise ValueError(
            f"repetition_penalty must be finite and > 0, got {repetition_penalty}")


def apply_repetition_penalty(logits, history, valid, penalty):
    """Divide positive / multiply negative logits of already-seen tokens by
    ``penalty`` (the CTRL rule). ``history`` is ``[S, C]`` int32 context
    ids, ``valid`` a ``[S, C]`` bool mask of which entries are real,
    ``penalty`` ``[S]`` with 1.0 as the exact no-op."""
    s, v = logits.shape
    rows = jnp.arange(s, dtype=jnp.int32)[:, None]
    ids = jnp.clip(history, 0, v - 1)
    seen = jnp.zeros((s, v), dtype=jnp.bool_).at[rows, ids].max(valid)
    pen = penalty[:, None].astype(logits.dtype)
    penalized = jnp.where(logits > 0, logits / pen, logits * pen)
    return jnp.where(seen, penalized, logits)


def apply_temperature(logits, temperature):
    """Scale by 1/T with a safe divisor — T=0 slots are decided by the
    greedy argmax select downstream, never by this branch's values."""
    t = jnp.maximum(temperature, 1e-6)[:, None].astype(logits.dtype)
    return logits / t


def apply_top_k(logits, top_k):
    """Keep the k highest logits per slot (ties at the k-th value survive).
    ``top_k`` is ``[S]`` int32; 0 disables. One descending sort serves every
    slot — k is a *value*, the kth threshold is a gather."""
    s, v = logits.shape
    desc = -jnp.sort(-logits, axis=-1)
    kth_idx = jnp.clip(top_k - 1, 0, v - 1)[:, None]
    kth = jnp.take_along_axis(desc, kth_idx, axis=-1)
    thresh = jnp.where((top_k > 0)[:, None], kth, _NEG_INF)
    return jnp.where(logits < thresh, _NEG_INF, logits)


def apply_top_p(logits, top_p):
    """Nucleus filter: keep the smallest prefix of the descending
    distribution with cumulative mass >= p (exclusive-mass test, so the
    top-1 token always survives). ``top_p`` is ``[S]``; >= 1 is an exact
    no-op (enforced by mask, not by trusting cumsum round-off)."""
    probs = jax.nn.softmax(logits, axis=-1)
    desc = -jnp.sort(-probs, axis=-1)
    exclusive = jnp.cumsum(desc, axis=-1) - desc
    keep_sorted = exclusive < top_p[:, None]
    min_kept = jnp.min(jnp.where(keep_sorted, desc, jnp.inf), axis=-1,
                       keepdims=True)
    keep = (probs >= min_kept) | (top_p >= 1.0)[:, None]
    return jnp.where(keep, logits, _NEG_INF)


def sample_tokens(logits, temperature, top_k, top_p, repetition_penalty,
                  seeds, positions, history, valid, logprobs_topk=0):
    """The sampler head. All inputs are per-slot value arrays over a fixed
    ``[S, V]`` logits block; returns
    ``(next_token[S] i32, chosen_logprob[S] f32,
       alt_ids[S, K] i32, alt_logprobs[S, K] f32)``
    with K = ``logprobs_topk`` (a static engine config, keyed into the AOT
    digest; K=0 yields empty panels). Fully traceable; compiles once."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    stochastic = temperature > 0

    def _stoch(lg):
        lg = apply_repetition_penalty(lg, history, valid, repetition_penalty)
        lg = apply_temperature(lg, temperature)
        # ONE descending sort serves both filters (XLA sorts dominate the
        # head's cost; apply_top_k/apply_top_p keep the reference one-filter
        # semantics but each pay for their own sort).
        v = lg.shape[-1]
        desc = -jnp.sort(-lg, axis=-1)
        # top-k threshold: the kth-largest logit (ties at kth survive);
        # k=0 disables via a -inf threshold.
        kth_idx = jnp.clip(top_k - 1, 0, v - 1)[:, None]
        kth = jnp.take_along_axis(desc, kth_idx, axis=-1)
        k_thresh = jnp.where((top_k > 0)[:, None], kth, _NEG_INF)
        # top-p threshold: softmax over the sorted row IS the sorted
        # distribution, so the exclusive-mass prefix maps straight back to
        # a logit threshold (the smallest kept logit; ties survive exactly
        # as in apply_top_p's prob-space test). p >= 1 is an exact no-op.
        p_desc = jax.nn.softmax(desc, axis=-1)
        exclusive = jnp.cumsum(p_desc, axis=-1) - p_desc
        keep_sorted = exclusive < top_p[:, None]
        n_keep = jnp.maximum(jnp.sum(keep_sorted, axis=-1), 1)
        pth = jnp.take_along_axis(desc, (n_keep - 1)[:, None], axis=-1)
        p_thresh = jnp.where((top_p < 1.0)[:, None], pth, _NEG_INF)
        thresh = jnp.maximum(k_thresh, p_thresh)
        lg = jnp.where(lg < thresh, _NEG_INF, lg)
        keys = slot_sample_keys(seeds, positions)
        def one(key, row):
            return jax.random.categorical(key, row)
        return jax.vmap(one)(keys, lg).astype(jnp.int32)

    sampled = jax.lax.cond(jnp.any(stochastic), _stoch,
                           lambda lg: greedy, logits)
    nxt = jnp.where(stochastic, sampled, greedy)

    logp = jax.nn.log_softmax(logits, axis=-1)
    chosen = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
    k = int(logprobs_topk)
    if k > 0:
        alt_lps, alt_ids = jax.lax.top_k(logp, k)
        alt_ids = alt_ids.astype(jnp.int32)
    else:
        s = logits.shape[0]
        alt_ids = jnp.zeros((s, 0), jnp.int32)
        alt_lps = jnp.zeros((s, 0), jnp.float32)
    return nxt, chosen, alt_ids, alt_lps
