"""Continuous-batching serving engine: ONE compiled decode step for every
tenant mix.

Reference analog: the reference's serving story is `AnalysisPredictor`
replaying a `fused_multi_transformer` program per request
(inference/api/analysis_predictor.h:95) — static batch, dense caches.
This engine is that layer rebuilt for the north star ("heavy traffic from
millions of users"), combining:

  * a **paged KV cache** (serving/cache.py): one preallocated block pool
    shared by every sequence, per-sequence block tables, admission /
    eviction / preemption as integer-table edits;
  * a **compiled decode step**: a single `jax.jit` executable over a
    fixed max-batch slot layout — ``(tokens [S], block_tables [S, M],
    seq_lens [S], active [S], k_pools, v_pools) -> (next_tokens,
    new_pools)`` with the pools donated. Requests joining or leaving the
    batch only change the *values* of the integer inputs, never a shape:
    the decode program compiles exactly once and then serves every token
    of every stream (`stats()["decode_compiles"]`, guarded by
    tools/perf_smoke.py);
  * **bucketed prefill**: prompts are right-padded to power-of-two
    length buckets, so admitting a new request compiles at most
    ``log2(max_context)`` prefill programs ever — and never touches the
    decode executable (`bucket_retrace` in the flight recorder marks
    each new bucket);
  * a **continuous-batching scheduler** (serving/scheduler.py): FCFS +
    free-block watermark admission, LIFO preempt-resume via block
    tables, join/leave at token boundaries;
  * **streaming detokenization**: per-request `on_token` callbacks fire
    the moment a token is produced (optionally through a tokenizer's
    `decode`), not when the request completes.

Telemetry rides the PR 4 fusion flight recorder: `serve.*` events
(enqueue/admit/step/evict/complete) with reason codes `kv_exhausted` /
`bucket_retrace`, aggregated by `profiler.explain` / `tools/fusion_doctor`
and benched by `tools/serve_bench.py` + the bench.py `serve` legs.
"""
from __future__ import annotations

import math
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import set_grad_enabled
from ..profiler.events import EVENTS as _EVENTS
from .cache import PagedKVCache, PagedCacheView, scatter_prefill
from .scheduler import (Request, Scheduler, RUNNING, FINISHED, FAILED)

__all__ = ["LLMEngine", "ServeStats"]

_MIN_BUCKET = 8


class ServeStats:
    """Engine counters + step-latency samples. `decode_compiles` is
    incremented INSIDE the traced decode function (the side effect runs
    only while tracing), so it counts real XLA traces — the zero-retrace
    guard reads it directly."""

    def __init__(self):
        self.reset()

    def reset(self):
        """Zero the counters IN PLACE: the compiled decode/prefill
        closures hold a reference to this object (that is how
        decode_compiles counts real traces), so a bench warmup resets the
        window without losing retrace visibility."""
        self.steps = 0
        self.tokens_generated = 0
        self.prefills = 0
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self.admitted = 0
        self.evictions = 0
        self.completed = 0
        self.failed = 0
        self.refused = 0
        self.occupancy_sum = 0.0
        self.saturated_steps = 0
        self.saturated_occupancy_sum = 0.0
        self.step_times_s = []
        self.wall_t0 = None
        self.wall_t1 = None

    def observe_step(self, active, num_slots, demand, dt_s):
        self.steps += 1
        occ = active / num_slots
        self.occupancy_sum += occ
        if demand >= num_slots:
            self.saturated_steps += 1
            self.saturated_occupancy_sum += occ
        if len(self.step_times_s) < 100_000:
            self.step_times_s.append(dt_s)

    def snapshot(self):
        times = sorted(self.step_times_s)

        def pct(p):
            if not times:
                return 0.0
            return times[min(len(times) - 1, int(p / 100.0 * len(times)))]

        elapsed = None
        if self.wall_t0 is not None and self.wall_t1 is not None:
            elapsed = self.wall_t1 - self.wall_t0
        return {
            "steps": self.steps,
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "decode_compiles": self.decode_compiles,
            "prefill_compiles": self.prefill_compiles,
            "admitted": self.admitted,
            "evictions": self.evictions,
            "completed": self.completed,
            "failed": self.failed,
            "refused": self.refused,
            "occupancy_mean": (self.occupancy_sum / self.steps
                               if self.steps else 0.0),
            "occupancy_saturated": (
                self.saturated_occupancy_sum / self.saturated_steps
                if self.saturated_steps else 0.0),
            "p50_step_ms": pct(50) * 1e3,
            "p99_step_ms": pct(99) * 1e3,
            "elapsed_s": elapsed,
            "tokens_per_sec": (self.tokens_generated / elapsed
                               if elapsed else 0.0),
        }


class LLMEngine:
    """Multi-tenant autoregressive serving over a GPT-family model.

    Usage::

        engine = LLMEngine(model, max_batch_size=8, block_size=16)
        engine.add_request([1, 2, 3], max_new_tokens=32,
                           on_token=lambda req, tok, text: ...)
        while engine.step():
            pass                      # or engine.run()

    Decoding is greedy (matches ``model.generate(do_sample=False)``
    token-for-token — the parity contract tests/test_serving.py pins).
    The model is put in eval mode and its parameters are BAKED into the
    compiled programs as constants (the engine owns the model for its
    lifetime); swapping weights means building a new engine.
    """

    def __init__(self, model, max_batch_size=8, block_size=16,
                 num_blocks=None, max_context=None, watermark_blocks=None,
                 dtype=None, tokenizer=None):
        cfg = model.config
        model.eval()
        self._model = model
        self._tokenizer = tokenizer
        self.max_batch_size = int(max_batch_size)
        self.block_size = int(block_size)
        self.max_context = int(max_context
                               or cfg.max_position_embeddings)
        self.max_blocks_per_seq = math.ceil(self.max_context
                                            / self.block_size)
        if num_blocks is None:
            # default: every slot can reach max_context (+ null block)
            num_blocks = 1 + self.max_batch_size * self.max_blocks_per_seq
        if dtype is None:
            params = model.parameters()
            dtype = params[0]._value.dtype if params else jnp.float32
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.cache = PagedKVCache(cfg.num_hidden_layers,
                                  cfg.num_attention_heads, head_dim,
                                  num_blocks, self.block_size, dtype)
        self.scheduler = Scheduler(self.max_batch_size,
                                   self.cache.allocator, self.block_size,
                                   watermark_blocks)
        self._stats = ServeStats()
        # fixed slot-layout state the compiled decode step consumes
        s, m = self.max_batch_size, self.max_blocks_per_seq
        self._tables = np.zeros((s, m), np.int32)
        self._lens = np.zeros(s, np.int32)
        self._active = np.zeros(s, bool)
        self._tokens = np.zeros(s, np.int32)
        self._k_pools = self.cache.k_pools
        self._v_pools = self.cache.v_pools
        self._decode_fn = None
        self._prefill_fns = {}
        self._next_rid = 0
        self.requests = {}

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=16, request_id=None,
                    eos_token_id=None, on_token=None):
        """Enqueue a generation request; returns the Request handle.

        Raises ValueError when the request can NEVER be served (prompt +
        max_new_tokens beyond the position table, or a peak KV footprint
        larger than the pool minus the growth watermark) — attributed as
        `kv_exhausted` in the flight recorder. A request that merely
        cannot fit *right now* is queued, not refused.
        """
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        rid = request_id
        if rid is None:
            rid = f"r{self._next_rid}"
        self._next_rid += 1
        prev = self.requests.get(rid)
        if prev is not None and not prev.finished:
            # overwriting would orphan a handle the scheduler still runs
            raise ValueError(
                f"request id {rid!r} is already queued/running; ids may "
                "only be reused after the previous request finishes")
        req = Request(rid, prompt, max_new_tokens, eos_token_id, on_token)
        if len(prompt) + req.max_new_tokens > self.max_context:
            raise ValueError(
                f"request {rid}: prompt ({len(prompt)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_context "
                f"({self.max_context})")
        sched = self.scheduler
        peak = sched.max_blocks_of(req)
        budget = sched.block_budget()
        if not sched.can_ever_fit(req):
            self._stats.refused += 1
            _EVENTS.emit("serve.enqueue", rid, reason="kv_exhausted",
                         detail={"blocks_needed": peak,
                                 "blocks_budget": budget})
            raise ValueError(
                f"request {rid}: needs {peak} KV blocks at peak but the "
                f"pool only ever has {budget} (capacity "
                f"{self.cache.allocator.capacity} - watermark "
                f"{sched.watermark_blocks}); refuse instead of deadlock")
        sched.enqueue(req)
        self.requests[rid] = req
        _EVENTS.emit("serve.enqueue", rid,
                     detail={"prompt_len": len(prompt),
                             "max_new_tokens": req.max_new_tokens})
        return req

    def step(self):
        """One engine iteration: admit at the token boundary, grow/evict
        for KV headroom, run the ONE compiled decode step, stream the
        produced tokens, retire finished requests. Returns True while
        any request is running or waiting."""
        if self._stats.wall_t0 is None:
            self._stats.wall_t0 = time.perf_counter()
        sched = self.scheduler
        # -- admission (token boundary) --------------------------------
        while True:
            req = sched.try_admit()
            if req is None:
                break
            self._admit(req)
        if not sched.running:
            self._stats.wall_t1 = time.perf_counter()
            return bool(sched.waiting)
        # -- KV growth, preempting (newest first) when the pool is dry --
        for req in sorted(list(sched.running),
                          key=lambda r: r.admit_seq):
            if req.state != RUNNING:
                continue
            need = sched.blocks_needed(req.cached_len)
            while len(req.blocks) < need and req.state == RUNNING:
                if sched.grow(req):
                    self._sync_slot(req)
                    continue
                victim = sched.preempt_victim(exclude=req)
                if victim is None:
                    self._fail(req, "kv_exhausted")
                    break
                self._evict(victim)
        if not sched.running:
            self._stats.wall_t1 = time.perf_counter()
            return bool(sched.waiting)
        # -- the ONE compiled decode step ------------------------------
        demand = sched.demand
        n_active = len(sched.running)
        t0 = time.perf_counter()
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
        nxt, self._k_pools, self._v_pools = self._decode_fn(
            self._tokens, self._tables, self._lens, self._active,
            self._k_pools, self._v_pools)
        toks = np.asarray(nxt)
        dt = time.perf_counter() - t0
        self._stats.observe_step(n_active, self.max_batch_size, demand, dt)
        _EVENTS.emit("serve.step", "engine",
                     detail={"active": n_active,
                             "occupancy": round(
                                 n_active / self.max_batch_size, 4),
                             "ms": round(dt * 1e3, 4)})
        # -- stream + retire -------------------------------------------
        for req in list(sched.running):
            slot = req.slot
            req.cached_len += 1
            self._lens[slot] = req.cached_len
            tok = int(toks[slot])
            self._tokens[slot] = tok
            self._emit_token(req, tok)
        self._stats.wall_t1 = time.perf_counter()
        return bool(sched.running or sched.waiting)

    def run(self, max_steps=None):
        """Drive step() until every request drains (or `max_steps`)."""
        n = 0
        while self.step():
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        return n

    def generate(self, prompts, max_new_tokens=16, eos_token_id=None):
        """Batch convenience: enqueue every prompt, run to drain, return
        the generated token lists (continuous batching under the hood —
        prompts of different lengths share slots and the block pool)."""
        reqs = [self.add_request(p, max_new_tokens,
                                 eos_token_id=eos_token_id)
                for p in prompts]
        self.run()
        for r in reqs:
            if r.state is FAILED:
                raise RuntimeError(f"request {r.rid} failed: {r.error}")
        return [list(r.generated) for r in reqs]

    def stats(self):
        snap = self._stats.snapshot()
        snap["scheduler"] = self.scheduler.info()
        snap["kv_blocks"] = self.cache.num_blocks
        snap["block_size"] = self.block_size
        return snap

    def reset_stats(self):
        """Start a fresh measurement window (counters AND step-time
        samples); the compiled programs and the KV pool are untouched, so
        a post-warmup window sees decode_compiles == 0 unless something
        actually retraced."""
        self._stats.reset()

    # ------------------------------------------------------------------
    # admission / prefill
    # ------------------------------------------------------------------
    @staticmethod
    def _bucket_for(n):
        return max(_MIN_BUCKET, 1 << (int(n - 1)).bit_length())

    def _admit(self, req):
        """Bucketed prefill of prompt + already-generated tokens (resume
        case) into the request's freshly assigned blocks, then join the
        decode batch. Never touches the decode executable."""
        ctx = req.prompt + req.generated
        bucket = self._bucket_for(len(ctx))
        fn = self._prefill_fns.get(bucket)
        new_bucket = fn is None
        if new_bucket:
            fn = self._build_prefill(bucket)
            self._prefill_fns[bucket] = fn
        self._stats.admitted += 1
        self._stats.prefills += 1
        _EVENTS.emit("serve.admit", req.rid,
                     reason="bucket_retrace" if new_bucket else None,
                     detail={"context_len": len(ctx), "bucket": bucket,
                             "blocks": len(req.blocks),
                             "resumed": bool(req.generated)})
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(ctx)] = ctx
        row = np.zeros(self.max_blocks_per_seq, np.int32)
        row[:len(req.blocks)] = req.blocks
        nxt, self._k_pools, self._v_pools = fn(
            padded, np.int32(len(ctx)), row,
            self._k_pools, self._v_pools)
        req.cached_len = len(ctx)
        self._sync_slot(req)
        tok = int(np.asarray(nxt))
        # the prefill's sampled token is the next decode step's input
        self._tokens[req.slot] = tok
        self._emit_token(req, tok)

    def _sync_slot(self, req):
        slot = req.slot
        row = np.zeros(self.max_blocks_per_seq, np.int32)
        row[:len(req.blocks)] = req.blocks
        self._tables[slot] = row
        self._lens[slot] = req.cached_len
        self._active[slot] = True

    def _clear_slot(self, slot):
        self._tables[slot] = 0
        self._lens[slot] = 0
        self._active[slot] = False
        self._tokens[slot] = 0

    # ------------------------------------------------------------------
    # token delivery / retirement
    # ------------------------------------------------------------------
    def _emit_token(self, req, tok):
        req.generated.append(tok)
        self._stats.tokens_generated += 1
        if req.first_token_ns is None:
            req.first_token_ns = time.perf_counter_ns()
        if req.on_token is not None:
            text = None
            if self._tokenizer is not None:
                try:
                    text = self._tokenizer.decode([tok])
                except Exception:
                    text = None
            req.on_token(req, tok, text)
        done = len(req.generated) >= req.max_new_tokens
        if req.eos_token_id is not None and tok == req.eos_token_id:
            done = True
        if done:
            self._finish(req)

    def _finish(self, req):
        slot = req.slot
        self.scheduler.release(req)
        if slot is not None:
            self._clear_slot(slot)
        req.state = FINISHED
        req.finish_ns = time.perf_counter_ns()
        self._stats.completed += 1
        _EVENTS.emit("serve.complete", req.rid,
                     detail={"tokens": len(req.generated),
                             "preemptions": req.preemptions})

    def _fail(self, req, why):
        slot = req.slot
        self.scheduler.release(req)
        if slot is not None:
            self._clear_slot(slot)
        req.state = FAILED
        req.error = why
        req.finish_ns = time.perf_counter_ns()
        self._stats.failed += 1
        _EVENTS.emit("serve.complete", req.rid, reason=why,
                     detail={"failed": True,
                             "tokens": len(req.generated)})

    def _evict(self, victim):
        """Preempt-resume: forget the victim's KV (a block-table edit),
        requeue at its arrival position; resume re-prefills."""
        slot = victim.slot
        self._stats.evictions += 1
        _EVENTS.emit("serve.evict", victim.rid, reason="kv_exhausted",
                     detail={"freed_blocks": len(victim.blocks),
                             "cached_tokens": victim.cached_len,
                             "preemptions": victim.preemptions + 1})
        self.scheduler.preempt(victim)
        if slot is not None:
            self._clear_slot(slot)

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _donate(self, argnums):
        # CPU ignores buffer donation (with a warning per program) —
        # only request it where it is real
        return argnums if jax.default_backend() != "cpu" else ()

    def _build_decode(self):
        model = self._model
        num_layers = model.config.num_hidden_layers
        block_size = self.block_size
        stats = self._stats

        def decode(tokens, tables, lens, active, k_pools, v_pools):
            stats.decode_compiles += 1   # runs only while tracing
            views = [PagedCacheView(k_pools[l], v_pools[l], tables, lens,
                                    active, block_size)
                     for l in range(num_layers)]
            with set_grad_enabled(False):
                logits, new_views = model(
                    Tensor(tokens[:, None], stop_gradient=True),
                    caches=views)
            new_k = jnp.stack([v.k_pool for v in new_views])
            new_v = jnp.stack([v.v_pool for v in new_views])
            nxt = jnp.argmax(logits._value[:, -1, :], axis=-1) \
                .astype(jnp.int32)
            return nxt, new_k, new_v

        return jax.jit(decode, donate_argnums=self._donate((4, 5)))

    def _build_prefill(self, bucket):
        model = self._model
        cfg = model.config
        num_layers = cfg.num_hidden_layers
        heads = cfg.num_attention_heads
        head_dim = cfg.hidden_size // heads
        block_size = self.block_size
        params = model.parameters()
        dt = params[0]._value.dtype if params else jnp.float32
        stats = self._stats

        def prefill(ids, length, block_row, k_pools, v_pools):
            stats.prefill_compiles += 1   # runs only while tracing
            empty = [(Tensor(jnp.zeros((1, 0, heads, head_dim), dt)),) * 2
                     for _ in range(num_layers)]
            with set_grad_enabled(False):
                logits, caches = model(Tensor(ids, stop_gradient=True),
                                       caches=[tuple(c) for c in empty])
            k_layers = jnp.stack([c[0]._value[0] for c in caches])
            v_layers = jnp.stack([c[1]._value[0] for c in caches])
            k_pools, v_pools = scatter_prefill(
                k_pools, v_pools, k_layers, v_layers, block_row, length,
                block_size)
            last = jax.lax.dynamic_index_in_dim(
                logits._value[0], length - 1, axis=0, keepdims=False)
            nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return nxt, k_pools, v_pools

        return jax.jit(prefill, donate_argnums=self._donate((3, 4)))
