"""Continuous-batching serving engine: ONE compiled decode step for every
tenant mix.

Reference analog: the reference's serving story is `AnalysisPredictor`
replaying a `fused_multi_transformer` program per request
(inference/api/analysis_predictor.h:95) — static batch, dense caches.
This engine is that layer rebuilt for the north star ("heavy traffic from
millions of users"), combining:

  * a **paged KV cache** (serving/cache.py): one preallocated block pool
    shared by every sequence, per-sequence block tables, admission /
    eviction / preemption as integer-table edits;
  * a **compiled decode step**: a single `jax.jit` executable over a
    fixed max-batch slot layout — ``(tokens [S], block_tables [S, M],
    seq_lens [S], active [S], k_pools, v_pools) -> (next_tokens,
    new_pools)`` with the pools donated. Requests joining or leaving the
    batch only change the *values* of the integer inputs, never a shape:
    the decode program compiles exactly once and then serves every token
    of every stream (`stats()["decode_compiles"]`, guarded by
    tools/perf_smoke.py);
  * **bucketed prefill**: prompts are right-padded to power-of-two
    length buckets, so admitting a new request compiles at most
    ``log2(max_context)`` prefill programs ever — and never touches the
    decode executable (`bucket_retrace` in the flight recorder marks
    each new bucket);
  * a **continuous-batching scheduler** (serving/scheduler.py): FCFS +
    free-block watermark admission, LIFO preempt-resume via block
    tables, join/leave at token boundaries;
  * **streaming detokenization**: per-request `on_token` callbacks fire
    the moment a token is produced (optionally through a tokenizer's
    `decode`), not when the request completes;
  * a **kernel tier** (PR 11): the decode step's paged attention runs
    blockwise streaming softmax over the block table
    (kernels/pallas/paged_attention.py — Pallas on TPU, a `lax.scan`
    twin elsewhere; `attention_kernel=` / FLAGS_serve_attention_kernel)
    instead of gathering a dense `[S, T, H, D]` context, and
    `kv_dtype="int8"` halves KV bytes per token via per-block-per-head
    scales (quantization/kv_cache.py) so the same pool admits ~2x the
    streams — both keyed into the dispatch cache and the AOT
    fingerprint, attributed via `kernel.fallback` / `kv_quantized`.

Resilience (PR 7, serving/resilience.py) rides every one of those layers:

  * **deadlines + cancellation** — `add_request(..., ttl_s=)` arms a
    per-request deadline checked at admission and at every iteration
    boundary; `cancel(request_id)` reclaims a stream the client gave up
    on. Expired/cancelled slots are VALUE edits to the fixed layout —
    the decode executable still compiles exactly once;
  * **bounded-queue backpressure** — `max_queue_depth` + an
    estimated-wait feasibility check refuse doomed work early with a
    structured `ServeRefusal` (`queue_full` / `deadline_infeasible` /
    `kv_exhausted`) instead of queueing it to rot, and the scheduler's
    aging guard keeps LIFO preemption from starving a long request;
  * **hung-step watchdog** — decode/prefill fires resolve through a
    monitored completion bounded by `FLAGS_serve_step_timeout_ms`; a
    stuck step emits `serve.hang`, marks the engine degraded, and climbs
    a recovery ladder (retry -> rebuild the decode executable -> fail
    the active requests with attributed reasons) instead of wedging;
  * **degraded-mode fallback** — a faulting/poisoned compiled decode
    finishes its in-flight streams per-request through the eager
    `generate()` path, token-identically, then rebuilds;
  * **crash-resume** — `state_payload()` / `restore_state()` snapshot
    the request/scheduler state (prompts, emitted tokens, arrival order
    — never the KV pool) so a kill-9'd server restarts and finishes
    every stream byte-identically (incubate.checkpoint.ServeCheckpointer
    + tools/chaos.py `serve_kill`).

Multi-tenancy (PR 17, serving/tenancy.py) makes the replica serve MANY
logical models and MANY users off the one compiled decode step:

  * **shared-prefix KV reuse** — `enable_prefix_cache=True` indexes
    every prefilled prompt's blocks by content hash; N streams sharing
    a system prompt alias the same refcounted blocks (admission
    allocates only the private remainder), pay its prefill once, and
    copy-on-write the first block a divergent token would land in;
  * **batched LoRA-style adapters** — `max_adapters=N` installs padded
    per-slot low-rank delta stacks as VALUE inputs to the decode
    executable; tenants join/leave/churn with zero retraces
    (`add_request(..., adapter=name)`, `register_adapter` /
    `unregister_adapter`);
  * **live weight hot-swap** — `hot_swap=True` passes the base weights
    as values too, so `swap_weights(new_values)` cuts every stream over
    to a new checkpoint at an exact iteration boundary (in-flight
    streams are preempted and re-prefill under the new weights, the
    prefix index is invalidated, the weight epoch bumps) — again zero
    retraces, attributed as `serve.swap`.

Telemetry rides the PR 4 fusion flight recorder: `serve.*` events
(enqueue/admit/step/evict/complete + cancel/expire/refuse/hang/degrade/
resume) with reason codes `kv_exhausted` / `bucket_retrace` /
`client_cancel` / `deadline_expired` / `queue_full` /
`deadline_infeasible` / `step_hang` / `decode_fault` / `crash_resume`,
aggregated by `profiler.explain` / `tools/fusion_doctor` and benched by
`tools/serve_bench.py` + the bench.py `serve` legs.
"""
from __future__ import annotations

import math
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.autograd import set_grad_enabled
from ..framework.flags import _FLAGS
from ..profiler.events import EVENTS as _EVENTS
from ..profiler.metrics import LogHistogram, SERVE as _M, \
    enabled as _metrics_on
from ..profiler import goodput as _goodput
from ..profiler import telemetry_server as _telemetry
from ..profiler import sentinel as _sentinel
from .cache import PagedKVCache, PagedCacheView, scatter_prefill, _is_int8
from .scheduler import (Request, Scheduler, QUEUED, RUNNING, FINISHED,
                        FAILED, CANCELLED, EXPIRED)
from .resilience import (ServeRefusal, MonitoredWait, StepHang,
                         request_payload, payload_request)
from .tenancy import PrefixCache, AdapterSet
from .sampling import SAMPLER_VERSION, validate_sampler, default_seed, \
    sample_tokens

__all__ = ["LLMEngine", "ServeStats"]

# recent step-time samples averaged into the admission-time wait estimate
_EST_WINDOW = 32

_MIN_BUCKET = 8


class ServeStats:
    """Engine counters + step-latency histograms. `decode_compiles` is
    incremented INSIDE the traced decode function (the side effect runs
    only while tracing), so it counts real XLA traces — the zero-retrace
    guard reads it directly.

    Latency percentiles come from bounded log-bucket streaming
    histograms (profiler/metrics.py LogHistogram): O(1) memory however
    long the engine runs, and FRESH — the old raw `step_times_s` list
    stopped appending at 100k samples, silently freezing p50/p99 for the
    rest of the process's life. `step_times_s` survives as a short
    recent-sample list (the admission-time wait estimate reads it)."""

    def __init__(self):
        self.reset()

    def reset(self):
        """Zero the counters IN PLACE: the compiled decode/prefill
        closures hold a reference to this object (that is how
        decode_compiles counts real traces), so a bench warmup resets the
        window without losing retrace visibility."""
        self.steps = 0
        self.tokens_generated = 0
        self.prefills = 0
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self.admitted = 0
        self.evictions = 0
        self.completed = 0
        self.failed = 0
        self.refused = 0
        # resilience counters (serving/resilience.py semantics)
        self.refused_queue_full = 0
        self.refused_deadline = 0
        self.cancelled = 0
        self.expired = 0
        self.hangs = 0
        self.eager_fallbacks = 0
        self.resumed = 0
        self.occupancy_sum = 0.0
        self.saturated_steps = 0
        self.saturated_occupancy_sum = 0.0
        # multi-tenant counters (PR 17): prefix_prompt_tokens is the
        # hit-rate denominator — every admitted context token that COULD
        # have aliased cached KV, hit or not
        self.prefix_hit_tokens = 0
        self.prefix_prompt_tokens = 0
        self.prefix_evictions = 0
        self.cow_copies = 0
        self.adapter_switches = 0
        self.weight_swaps = 0
        # compiled stochastic sampling + pipelined decode (PR 18):
        # sampled_tokens counts committed tokens from slots decoding with
        # temperature > 0 (greedy slots are the same program, different
        # values); commit_rollbacks counts speculative tokens a lag-1
        # commit discarded because the slot's request was cancelled /
        # expired / preempted / finished between launch and commit
        self.sampled_tokens = 0
        self.commit_rollbacks = 0
        # recent raw samples only (the admission wait estimate averages
        # the tail); percentiles live in the windowed histograms below
        self.step_times_s = []
        self.step_hist = LogHistogram()
        self.ttft_hist = LogHistogram()
        self.inter_token_hist = LogHistogram()
        self.queue_wait_hist = LogHistogram()
        self.wall_t0 = None
        self.wall_t1 = None

    def observe_step(self, active, num_slots, demand, dt_s):
        self.steps += 1
        occ = active / num_slots
        self.occupancy_sum += occ
        if demand >= num_slots:
            self.saturated_steps += 1
            self.saturated_occupancy_sum += occ
        self.step_times_s.append(dt_s)
        if len(self.step_times_s) > 4 * _EST_WINDOW:
            del self.step_times_s[:-_EST_WINDOW]
        self.step_hist.observe(dt_s)

    def snapshot(self):
        def pct(p):
            return self.step_hist.percentile(p)

        elapsed = None
        if self.wall_t0 is not None and self.wall_t1 is not None:
            elapsed = self.wall_t1 - self.wall_t0
        return {
            "steps": self.steps,
            "tokens_generated": self.tokens_generated,
            "prefills": self.prefills,
            "decode_compiles": self.decode_compiles,
            "prefill_compiles": self.prefill_compiles,
            "admitted": self.admitted,
            "evictions": self.evictions,
            "completed": self.completed,
            "failed": self.failed,
            "refused": self.refused,
            "refused_queue_full": self.refused_queue_full,
            "refused_deadline": self.refused_deadline,
            "cancelled": self.cancelled,
            "expired": self.expired,
            "hangs": self.hangs,
            "eager_fallbacks": self.eager_fallbacks,
            "resumed": self.resumed,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_hit_rate": (self.prefix_hit_tokens
                                / self.prefix_prompt_tokens
                                if self.prefix_prompt_tokens else 0.0),
            "prefix_evictions": self.prefix_evictions,
            "cow_copies": self.cow_copies,
            "adapter_switches": self.adapter_switches,
            "weight_swaps": self.weight_swaps,
            "sampled_tokens": self.sampled_tokens,
            "commit_rollbacks": self.commit_rollbacks,
            "occupancy_mean": (self.occupancy_sum / self.steps
                               if self.steps else 0.0),
            "occupancy_saturated": (
                self.saturated_occupancy_sum / self.saturated_steps
                if self.saturated_steps else 0.0),
            "p50_step_ms": pct(50) * 1e3,
            "p99_step_ms": pct(99) * 1e3,
            # request-latency percentiles (PR 12): TTFT (enqueue ->
            # first token), inter-token gap, and admission queue wait,
            # all from the same bounded windowed histograms
            "ttft_p50_ms": self.ttft_hist.percentile(50) * 1e3,
            "ttft_p99_ms": self.ttft_hist.percentile(99) * 1e3,
            "inter_token_p50_ms":
                self.inter_token_hist.percentile(50) * 1e3,
            "inter_token_p99_ms":
                self.inter_token_hist.percentile(99) * 1e3,
            "queue_wait_p50_ms":
                self.queue_wait_hist.percentile(50) * 1e3,
            "queue_wait_p99_ms":
                self.queue_wait_hist.percentile(99) * 1e3,
            "elapsed_s": elapsed,
            "tokens_per_sec": (self.tokens_generated / elapsed
                               if elapsed else 0.0),
        }


class LLMEngine:
    """Multi-tenant autoregressive serving over a GPT-family model.

    Usage::

        engine = LLMEngine(model, max_batch_size=8, block_size=16)
        engine.add_request([1, 2, 3], max_new_tokens=32,
                           on_token=lambda req, tok, text: ...)
        while engine.step():
            pass                      # or engine.run()

    Decoding is greedy (matches ``model.generate(do_sample=False)``
    token-for-token — the parity contract tests/test_serving.py pins).
    The model is put in eval mode; by default its parameters are BAKED
    into the compiled programs as constants. `hot_swap=True` and/or
    `max_adapters>0` switch the programs to the multi-tenant signature
    (serving/tenancy.py): the weights / adapter stacks become VALUE
    inputs, so `swap_weights()` refreshes the base checkpoint mid-traffic
    and tenants churn adapters with zero retraces.
    `enable_prefix_cache=True` adds shared-prefix KV block aliasing with
    copy-on-write — N streams sharing a system prompt pay its prefill
    and its KV bytes once.
    """

    def __init__(self, model, max_batch_size=8, block_size=16,
                 num_blocks=None, max_context=None, watermark_blocks=None,
                 dtype=None, tokenizer=None, max_queue_depth=None,
                 aging_max_preemptions=3, kv_dtype=None,
                 attention_kernel=None, enable_prefix_cache=False,
                 max_adapters=0, adapter_rank=4, hot_swap=False,
                 logprobs_topk=0, pipeline_decode=False):
        cfg = model.config
        model.eval()
        self._model = model
        self._tokenizer = tokenizer
        self.max_batch_size = int(max_batch_size)
        self.block_size = int(block_size)
        self.max_context = int(max_context
                               or cfg.max_position_embeddings)
        self.max_blocks_per_seq = math.ceil(self.max_context
                                            / self.block_size)
        if num_blocks is None:
            # default: every slot can reach max_context (+ null block)
            num_blocks = 1 + self.max_batch_size * self.max_blocks_per_seq
        self._num_blocks = num_blocks
        if dtype is None:
            params = model.parameters()
            dtype = params[0]._value.dtype if params else jnp.float32
        self._dtype = dtype
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        # kv_dtype="int8" stores the pool quantized (per-block-per-head
        # scales, quantization/kv_cache.py) — half the bytes per cached
        # token, so the same pool admits ~2x the streams
        self._kv_dtype = dtype if kv_dtype is None else (
            jnp.int8 if _is_int8(kv_dtype) else kv_dtype)
        self._kv_quantized = _is_int8(self._kv_dtype)
        # resolve the attention variant ONCE: the compiled decode step
        # bakes it in (zero retraces under churn); a flag flip only
        # affects engines built after it
        from ..nn.functional.attention import resolve_paged_kernel
        self._attn_kernel = resolve_paged_kernel(
            attention_kernel, head_dim=head_dim, block_size=self.block_size)
        if self._kv_quantized:
            _EVENTS.emit("kernel.quantized", "serve.decode",
                         reason="kv_quantized",
                         detail={"kv_dtype": "int8",
                                 "kernel": self._attn_kernel,
                                 "num_blocks": int(num_blocks),
                                 "block_size": self.block_size})
        self.cache = PagedKVCache(cfg.num_hidden_layers,
                                  cfg.num_attention_heads, head_dim,
                                  num_blocks, self.block_size,
                                  self._kv_dtype)
        self.scheduler = Scheduler(self.max_batch_size,
                                   self.cache.allocator, self.block_size,
                                   watermark_blocks,
                                   max_queue_depth=max_queue_depth,
                                   aging_max_preemptions=
                                   aging_max_preemptions)
        # -- multi-tenant layer (PR 17, serving/tenancy.py) -------------
        # prefix cache: content-addressed aliasing of prompt KV blocks
        self._prefix = (PrefixCache(self.cache.allocator, self.block_size)
                        if enable_prefix_cache else None)
        # batched adapters: padded low-rank stacks as decode VALUE inputs
        self._adapters = (AdapterSet(model, max_adapters, adapter_rank,
                                     dtype=self._dtype)
                          if max_adapters > 0 else None)
        self._hot_swap = bool(hot_swap)
        # aux-input mode: the decode/prefill signatures gain an `aux`
        # pytree (weights as values / adapter stacks + slot indices);
        # with both features off the signatures stay byte-identical to
        # the single-tenant engine
        self._tenant = self._hot_swap or self._adapters is not None
        self._holder = None
        if self._adapters is not None:
            holder = getattr(model, "_tenancy_holder", None)
            if holder is None:
                holder = {"active": None}
                model._tenancy_holder = holder
            self._holder = holder
            self._adapters.install(holder)
        self._weight_epoch = 0
        self._pending_weights = None
        self._weights_crc = self._params_crc() if self._hot_swap else None
        self._cow_fn = None
        self._stats = ServeStats()
        self._monitor = MonitoredWait()
        # degraded-mode latch: set by the watchdog / a decode fault,
        # cleared by the first clean decode step afterwards (both
        # transitions emit serve.degrade so the flight recorder shows the
        # full degraded window)
        self.degraded = False
        # fixed slot-layout state the compiled decode step consumes
        s, m = self.max_batch_size, self.max_blocks_per_seq
        self._tables = np.zeros((s, m), np.int32)
        self._lens = np.zeros(s, np.int32)
        self._active = np.zeros(s, bool)
        self._tokens = np.zeros(s, np.int32)
        # per-slot adapter index into the padded stacks (0 = base);
        # deliberately NOT reset by _clear_slot — a stale index on an
        # inactive slot is masked out, and clearing it would count a
        # spurious adapter switch on the next same-tenant admission
        self._aslots = np.zeros(s, np.int32)
        # -- compiled stochastic sampling (PR 18, serving/sampling.py) --
        # per-slot sampler config as fixed [S] VALUE buffers — edited
        # like tokens/lens on join/leave, never reshaping, so arbitrary
        # per-slot sampler churn keeps decode_compiles == 1. Greedy is
        # temperature=0 under the same program; the no-op values below
        # keep a cleared slot on the cheap all-greedy cond branch
        self._logprobs_topk = int(logprobs_topk)
        self._temps = np.zeros(s, np.float32)
        self._topks = np.zeros(s, np.int32)
        self._topps = np.ones(s, np.float32)
        self._rpens = np.ones(s, np.float32)
        self._seeds = np.zeros(s, np.uint32)
        # per-slot context-token history for the in-graph repetition
        # penalty; positions <= lens are valid. The decode step scatters
        # its own input token at index `lens` in-graph, so the one token
        # the host has not committed yet (pipelined mode) is still seen
        self._history = np.zeros((s, self.max_context), np.int32)
        # -- software-pipelined decode (PR 18) --------------------------
        # launch step N+1 against device-fed tokens while step N's host
        # commit overlaps: `_inflight` holds the un-committed launch,
        # `_feedback` the device next-token array it will consume, and
        # `_override[slot]` marks slots whose HOST token (admission /
        # chew / restore) must win over the device feedback
        self._pipeline = bool(pipeline_decode)
        self._inflight = None
        self._feedback = None
        self._override = np.ones(s, bool)
        self._k_pools = self.cache.k_pools
        self._v_pools = self.cache.v_pools
        self._k_scales = self.cache.k_scales       # None unless int8 KV
        self._v_scales = self.cache.v_scales
        self._decode_fn = None
        self._prefill_fns = {}
        # AOT warm start (ops/aot_cache.py): the decode digest is computed
        # lazily (it CRCs the weights once); a pending-store tuple means
        # the first successful decode step should persist the executable
        self._aot_digest_cache = None
        self._aot_pending_store = None
        self._next_rid = 0
        # rid -> Request: the id registry (duplicate-id checks, cancel(),
        # introspection). Terminal handles are retained until the caller
        # drains them with pop_finished() — live scheduling state lives
        # in scheduler.waiting/running, never here
        self.requests = {}
        # True while step() is mutating the slot arrays: a cancel()
        # issued from inside a streaming callback then defers to the
        # next iteration boundary instead of editing the layout under
        # the loop's feet
        self._stepping = False
        # liveness heartbeat (profiler/telemetry_server.py /healthz):
        # stamped at step entry and after every clean decode step, so a
        # busy engine whose heartbeat goes stale past the watchdog
        # window reads as wedged — even when the wedge is a blind C++
        # hang the watchdog itself cannot interrupt
        self._hb_ns = None
        # stamped whenever a fresh executable is about to trace (first
        # decode build, a new prefill bucket, watchdog rebuilds):
        # /healthz widens its staleness window during the compile so a
        # supervisor never kills a replica for legitimately compiling
        self._compile_grace_ns = None
        _telemetry.maybe_start_from_flags()
        _telemetry.register_engine(self)
        _sentinel.maybe_arm_from_flags()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=16, request_id=None,
                    eos_token_id=None, on_token=None, ttl_s=None,
                    adapter=None, temperature=0.0, top_k=0, top_p=1.0,
                    repetition_penalty=1.0, seed=None):
        """Enqueue a generation request; returns the Request handle.

        `temperature` / `top_k` / `top_p` / `repetition_penalty` / `seed`
        configure the stream's sampler — VALUES in the one compiled
        decode step (serving/sampling.py), so a batch may mix greedy and
        any number of distinct sampler configs with zero retraces.
        ``temperature=0`` (the default) is greedy under the same program,
        token-identical to ``model.generate(do_sample=False)``; the other
        knobs are inert at temperature 0. `seed` defaults to a stable
        hash of the request id; a given (seed, prompt, sampler config)
        reproduces its stream byte-identically across preemption,
        watchdog rebuild, and crash resume. Out-of-contract values are
        refused as `sampler_mismatch`.

        `ttl_s` arms a deadline: the request is expired (attributed
        `deadline_expired`) if the TTL passes while it waits or runs.

        `adapter` names the registered LoRA-style adapter this stream
        decodes under (None = base weights); an unknown name is refused
        as `adapter_mismatch` — silently serving base weights to a
        tenant that asked for its fine-tune would be a correctness bug,
        not a degraded mode.

        Raises `ServeRefusal` (a ValueError) when admission would be
        doomed work, each refusal attributed in the flight recorder as a
        `serve.refuse` event:

          * `queue_full` — the bounded waiting queue is at
            `max_queue_depth`;
          * `kv_exhausted` — the peak KV footprint can NEVER fit in the
            pool minus the growth watermark;
          * `deadline_infeasible` — the TTL is already spent, or the
            estimated queue wait + service time exceeds it.

        A request that merely cannot fit *right now* is queued, not
        refused. Plain validation errors (empty prompt, context
        overflow, duplicate live id) stay ValueError.
        """
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        rid = request_id
        if rid is None:
            rid = f"r{self._next_rid}"
        self._next_rid += 1
        prev = self.requests.get(rid)
        if prev is not None and not prev.finished:
            # overwriting would orphan a handle the scheduler still runs
            raise ValueError(
                f"request id {rid!r} is already queued/running; ids may "
                "only be reused after the previous request finishes")
        req = Request(rid, prompt, max_new_tokens, eos_token_id, on_token,
                      ttl_s=ttl_s, adapter=adapter,
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      repetition_penalty=repetition_penalty,
                      seed=(default_seed(rid) if seed is None
                            else int(seed) & 0xFFFFFFFF))
        try:
            validate_sampler(temperature, top_k, top_p, repetition_penalty)
        except ValueError as e:
            self._refuse(req, "sampler_mismatch",
                         f"request {rid}: {e}",
                         {"temperature": temperature, "top_k": top_k,
                          "top_p": top_p,
                          "repetition_penalty": repetition_penalty})
        if len(prompt) + req.max_new_tokens > self.max_context:
            raise ValueError(
                f"request {rid}: prompt ({len(prompt)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max_context "
                f"({self.max_context})")
        if adapter is not None and (
                self._adapters is None
                or not self._adapters.is_registered(adapter)):
            self._refuse(req, "adapter_mismatch",
                         f"request {rid}: adapter {adapter!r} is not "
                         "registered with this engine; register it (or "
                         "build the engine with max_adapters > 0) before "
                         "routing its tenant here",
                         {"adapter": adapter,
                          "registered": ([] if self._adapters is None
                                         else self._adapters.names())})
        self._admission_policy(req)
        self.scheduler.enqueue(req)
        self.requests[rid] = req
        _EVENTS.emit("serve.enqueue", rid,
                     detail={"prompt_len": len(prompt),
                             "max_new_tokens": req.max_new_tokens,
                             "ttl_s": ttl_s})
        if req.temperature > 0:
            # sampler lifecycle attribution: one event per stochastic
            # stream, carrying the full resolved config — the flight
            # recorder's proof that sampler churn stayed value-only
            _EVENTS.emit("serve.sample", rid,
                         detail={"temperature": req.temperature,
                                 "top_k": req.top_k, "top_p": req.top_p,
                                 "repetition_penalty":
                                     req.repetition_penalty,
                                 "seed": req.seed})
        return req

    def _admission_policy(self, req):
        """Refuse-early backpressure: raise `ServeRefusal` (and emit the
        attributed `serve.refuse` event) for work that is doomed at
        enqueue time. Checked in cost order: queue depth (free), pool
        feasibility (arithmetic), deadline feasibility (needs latency
        samples)."""
        sched = self.scheduler
        if sched.queue_full():
            self._refuse(req, "queue_full",
                         f"request {req.rid}: waiting queue is at "
                         f"max_queue_depth ({sched.max_queue_depth}); "
                         "shed load upstream or add capacity",
                         {"queue_depth": len(sched.waiting),
                          "max_queue_depth": sched.max_queue_depth})
        peak = sched.max_blocks_of(req)
        budget = sched.block_budget()
        shared = 0
        if self._prefix is not None:
            # aliasing credit: blocks this prompt would inherit by
            # reference rather than allocate (counted ONCE — the PR 17
            # accounting bugfix; advisory, so no references are taken)
            shared, _ = self._prefix.probe(req.prompt + req.generated)
        if not sched.can_ever_fit(req, shared_blocks=shared):
            self._refuse(req, "kv_exhausted",
                         f"request {req.rid}: needs {peak} KV blocks at "
                         f"peak but the pool only ever has {budget} "
                         f"(capacity {self.cache.allocator.capacity} - "
                         f"watermark {sched.watermark_blocks}); refuse "
                         "instead of deadlock",
                         {"blocks_needed": peak, "blocks_budget": budget})
        if req.deadline_ns is None:
            return
        remaining = req.ttl_remaining_s()
        if remaining <= 0:
            self._refuse(req, "deadline_infeasible",
                         f"request {req.rid}: deadline already expired "
                         "at enqueue",
                         {"ttl_remaining_s": round(remaining, 6)})
        times = self._stats.step_times_s
        if times:
            avg = sum(times[-_EST_WINDOW:]) / len(times[-_EST_WINDOW:])
            need_steps = sched.estimated_wait_steps(req) \
                + req.max_new_tokens
            est = need_steps * avg
            if est > remaining:
                self._refuse(
                    req, "deadline_infeasible",
                    f"request {req.rid}: estimated wait + service "
                    f"{est:.3f}s exceeds the remaining TTL "
                    f"{remaining:.3f}s; refusing now beats expiring "
                    "later",
                    {"estimated_s": round(est, 4),
                     "ttl_remaining_s": round(remaining, 4),
                     "est_steps": need_steps})

    def _refuse(self, req, reason, message, detail):
        self._stats.refused += 1
        if reason == "queue_full":
            self._stats.refused_queue_full += 1
        elif reason == "deadline_infeasible":
            self._stats.refused_deadline += 1
        if _metrics_on():
            _M.refusals.labels(reason=reason).inc()
        _EVENTS.emit("serve.refuse", req.rid, reason=reason, detail=detail)
        raise ServeRefusal(reason, message, detail)

    def cancel(self, request_id):
        """Client cancellation: reclaim the stream's slot/KV at the next
        safe point. Between steps (the usual driver loop) the request is
        cleared immediately; a cancel issued from inside a streaming
        `on_token` callback — i.e. while step() is mid-iteration over
        the slot arrays — is deferred to the next boundary sweep so the
        fixed layout is only ever edited between decode steps. Either
        way the edit is value-only: the decode executable never
        retraces. Returns True when the request was live, False when it
        was unknown or already terminal (cancel racing completion is a
        no-op)."""
        req = self.requests.get(request_id)
        if req is None or req.finished:
            return False
        req.cancel_requested = True
        if self._stepping and req.slot is not None:
            return True          # boundary sweep picks it up next step
        self._cancel_now(req)
        return True

    def _cancel_now(self, req):
        slot = req.slot
        self.scheduler.remove_waiting(req)
        self.scheduler.release(req)
        if slot is not None:
            self._clear_slot(slot)
        req.state = CANCELLED
        req.error = "client_cancel"
        req.finish_ns = time.perf_counter_ns()
        self._stats.cancelled += 1
        if _metrics_on():
            _M.requests.labels(outcome="cancelled").inc()
        _EVENTS.emit("serve.cancel", req.rid, reason="client_cancel",
                     detail={"was_running": slot is not None,
                             "tokens": len(req.generated)})

    def _expire(self, req):
        """Deadline passed while queued or running: clear the request
        (value-only slot edit) and attribute the decision."""
        slot = req.slot
        where = "running" if slot is not None else "queued"
        self.scheduler.remove_waiting(req)
        self.scheduler.release(req)
        if slot is not None:
            self._clear_slot(slot)
        req.state = EXPIRED
        req.error = "deadline_expired"
        req.finish_ns = time.perf_counter_ns()
        self._stats.expired += 1
        if _metrics_on():
            _M.requests.labels(outcome="expired").inc()
        _EVENTS.emit("serve.expire", req.rid, reason="deadline_expired",
                     detail={"where": where,
                             "tokens": len(req.generated)})

    def _boundary_housekeeping(self):
        """Iteration-boundary sweep: honor cancels deferred from inside
        streaming callbacks, then expire queued requests (an expired
        head must never block FCFS admission) and running ones (their
        slots free up for admission this very boundary)."""
        sched = self.scheduler
        for req in [r for r in list(sched.waiting) + list(sched.running)
                    if r.cancel_requested]:
            self._cancel_now(req)
        now = time.perf_counter_ns()
        for req in sched.expired_waiting(now):
            self._expire(req)
        for req in [r for r in list(sched.running) if r.expired(now)]:
            self._expire(req)

    def step(self):
        """One engine iteration: expire/cancel at the boundary, admit,
        grow/evict for KV headroom, run the ONE compiled decode step
        under the watchdog, stream the produced tokens, retire finished
        requests. Returns True while any request is running or
        waiting."""
        if self._stats.wall_t0 is None:
            self._stats.wall_t0 = time.perf_counter()
        self._hb_ns = time.perf_counter_ns()
        sched = self.scheduler
        self._stepping = True
        try:
            return self._step_locked()
        finally:
            self._stepping = False

    def _step_locked(self):
        sched = self.scheduler
        # -- weight hot-swap cutover (exact iteration boundary) --------
        if self._pending_weights is not None:
            self._commit_swap()
        # -- cancel/deadline sweep + admission (token boundary) --------
        self._boundary_housekeeping()
        hook = self._prefix_hook if self._prefix is not None else None
        while True:
            # expire a dead head BEFORE admission assigns it a slot —
            # it never ran, and the serve.expire where=queued/running
            # split must stay truthful for queue-sizing diagnosis
            while sched.waiting and sched.waiting[0].expired():
                self._expire(sched.waiting[0])
            req = sched.try_admit(prefix_hook=hook)
            if req is None:
                # the pool may be dry only because the prefix index is
                # hoarding cold entries — release those and retry before
                # giving up on this boundary (only when a slot is
                # actually free: batch pressure is not block pressure)
                if (self._prefix is not None and sched.waiting
                        and None in sched.slots
                        and self._reclaim_prefix(
                            sched.blocks_needed(
                                sched.waiting[0].context_len)
                            + sched.watermark_blocks)):
                    continue
                break
            self._admit(req)
        if not sched.running:
            if self._pipeline:
                self._flush_inflight()
            self._stats.wall_t1 = time.perf_counter()
            return bool(sched.waiting)
        # -- KV growth, preempting (newest first) when the pool is dry --
        for req in sorted(list(sched.running),
                          key=lambda r: r.admit_seq):
            if req.state != RUNNING:
                continue
            need = sched.blocks_needed(req.cached_len)
            while len(req.blocks) < need and req.state == RUNNING:
                if sched.grow(req):
                    self._sync_slot(req)
                    continue
                if self._prefix is not None and self._reclaim_prefix(1):
                    continue    # cold prefix entries go before tenants
                victim = sched.preempt_victim(exclude=req)
                if victim is not None:
                    self._evict(victim)
                    continue
                if not sched.protected(req):
                    # aging guard: every other tenant is protected —
                    # the grower steps aside (requeued, not failed)
                    self._evict(req)
                    break
                self._fail(req, "kv_exhausted")
                break
        if not sched.running:
            if self._pipeline:
                self._flush_inflight()
            self._stats.wall_t1 = time.perf_counter()
            return bool(sched.waiting)
        # -- copy-on-write boundary: privatize shared write targets ----
        if self._prefix is not None:
            self._cow_sweep()
            if not sched.running:
                if self._pipeline:
                    self._flush_inflight()
                self._stats.wall_t1 = time.perf_counter()
                return bool(sched.waiting)
        # -- software-pipelined tail: launch N+1, commit N (lag 1) -----
        if self._pipeline:
            return self._step_pipelined()
        # -- the ONE compiled decode step (watchdog-monitored) ---------
        demand = sched.demand
        n_active = len(sched.running)
        t0 = time.perf_counter()
        out = self._decode_step()
        if out is None:
            # ladder rung 3 / eager fallback retired the batch; the
            # engine stays serviceable for queued + new work. Any stall
            # booked inside the abandoned step must not be subtracted
            # from the NEXT (unrelated) productive step's time
            if _metrics_on():
                _goodput.ACCOUNTANT.drop_stall_carry()
            self._stats.wall_t1 = time.perf_counter()
            return bool(sched.running or sched.waiting)
        dt = time.perf_counter() - t0
        self._stats.observe_step(n_active, self.max_batch_size, demand, dt)
        self._hb_ns = time.perf_counter_ns()
        # a completed step means any pending compile finished: the
        # /healthz grace window closes and staleness reverts to the
        # watchdog budget
        self._compile_grace_ns = None
        _telemetry.beat("decode", step=self._stats.steps)
        _sentinel.tick()
        if _metrics_on():
            _M.step_s.observe(dt)
            _M.occupancy.set(n_active / self.max_batch_size)
            # productive serving time: the goodput fraction stays
            # meaningful in a process that never crosses an optimizer
            # boundary (stall time lands via the watchdog's note_stall)
            _goodput.ACCOUNTANT.note_productive(dt)
        _EVENTS.emit("serve.step", "engine",
                     detail={"active": n_active,
                             "occupancy": round(
                                 n_active / self.max_batch_size, 4),
                             "ms": round(dt * 1e3, 4)})
        if self.degraded:
            # first clean decode step after a hang/fault: recovered
            self.degraded = False
            _EVENTS.emit("serve.degrade", "engine",
                         detail={"recovered": True})
        # -- stream + retire -------------------------------------------
        toks, logps, aids, alps = out
        for req in list(sched.running):
            if req.finished or req.slot is None:
                # retired mid-loop (a streaming callback cancelled it);
                # its token from this launch is dropped on the floor
                continue
            slot = req.slot
            req.cached_len += 1
            self._lens[slot] = req.cached_len
            if req.chew:
                # prefix-hit warm-up: the next context token is already
                # KNOWN — feed it as the next decode input and drop the
                # prediction (made from a mid-context position, it is
                # not this stream's next output token)
                t = req.chew.pop(0)
                self._tokens[slot] = t
                if req.cached_len < self.max_context:
                    self._history[slot, req.cached_len] = t
                continue
            tok = int(toks[slot])
            self._tokens[slot] = tok
            if req.cached_len < self.max_context:
                self._history[slot, req.cached_len] = tok
            self._emit_token(req, tok, logp=float(logps[slot]),
                             alts=((aids[slot], alps[slot])
                                   if self._logprobs_topk else None))
        self._stats.wall_t1 = time.perf_counter()
        return bool(sched.running or sched.waiting)

    # ------------------------------------------------------------------
    # software-pipelined decode (PR 18): launch N+1, commit N at lag 1
    # ------------------------------------------------------------------
    def _step_pipelined(self):
        """Pipelined tail of one iteration: LAUNCH this step's decode
        against device-fed tokens (the previous launch's sampled ids
        feed back as a device array — no host round-trip), then COMMIT
        the previous launch's host work (detokenize, callbacks,
        retirement) while the device runs the new one. Steady-state step
        time is max(device, host-commit) instead of their sum, and the
        watchdog's monitored wait only ever covers device time."""
        sched = self.scheduler
        demand = sched.demand
        n_active = len(sched.running)
        t0 = time.perf_counter()
        launched = self._launch_decode()
        ok = self._commit_inflight()
        if not ok:
            # destructive recovery fired mid-window: the launch just
            # issued consumed suspect pool/token state — discard it too
            if launched is not None:
                self._discard_records(launched)
            self._reset_pipeline()
            if _metrics_on():
                _goodput.ACCOUNTANT.drop_stall_carry()
            self._stats.wall_t1 = time.perf_counter()
            return bool(sched.running or sched.waiting)
        self._inflight = launched
        if launched is None:
            self._stats.wall_t1 = time.perf_counter()
            return bool(sched.running or sched.waiting)
        dt = time.perf_counter() - t0
        self._stats.observe_step(n_active, self.max_batch_size, demand,
                                 dt)
        self._hb_ns = time.perf_counter_ns()
        self._compile_grace_ns = None
        _telemetry.beat("decode", step=self._stats.steps)
        _sentinel.tick()
        if _metrics_on():
            _M.step_s.observe(dt)
            _M.occupancy.set(n_active / self.max_batch_size)
            _goodput.ACCOUNTANT.note_productive(dt)
        _EVENTS.emit("serve.step", "engine",
                     detail={"active": n_active,
                             "occupancy": round(
                                 n_active / self.max_batch_size, 4),
                             "ms": round(dt * 1e3, 4),
                             "pipelined": True})
        if self.degraded:
            self.degraded = False
            _EVENTS.emit("serve.degrade", "engine",
                         detail={"recovered": True})
        self._stats.wall_t1 = time.perf_counter()
        return bool(sched.running or sched.waiting
                    or self._inflight is not None)

    def _launch_decode(self):
        """Dispatch one decode launch asynchronously. Structural state
        (cached_len, lens, chew) advances HERE — the KV write at
        position `lens` is certain regardless of what token the launch
        samples — while token-dependent state (generated, callbacks,
        finish) waits for the lag-1 commit. Returns the inflight record,
        or None when no slot can accept another token."""
        sched = self.scheduler
        if not sched.running:
            return None
        if self._decode_fn is None:
            self._compile_grace_ns = time.perf_counter_ns()
            self._decode_fn = self._build_decode()
        launch_active = self._active.copy()
        plan = []
        for req in list(sched.running):
            if req.state != RUNNING or req.slot is None:
                continue
            slot = req.slot
            pending = 1 if self._has_pending(req, slot) else 0
            if (not req.chew
                    and len(req.generated) + pending
                    >= req.max_new_tokens):
                # every remaining token is committed or in flight —
                # launching this slot could only overshoot max_new
                launch_active[slot] = False
                continue
            plan.append((req, slot))
        if not plan:
            return None
        tokens_in = self._tokens
        if self._feedback is not None and not self._override.all():
            if self._override.any():
                # mixed: device feedback for slots whose last token
                # exists only on-device, host-authored tokens
                # (admission/chew/restore) win via the override mask
                tokens_in = jnp.where(jnp.asarray(self._override),
                                      jnp.asarray(self._tokens),
                                      self._feedback).astype(jnp.int32)
            else:
                # steady state (no joins/chew since the last launch):
                # the previous launch's output feeds straight back in —
                # zero host round-trip, zero extra dispatches
                tokens_in = self._feedback
        base = (tokens_in, self._tables, self._lens, launch_active)
        if self._tenant:
            base = base + (self._decode_aux(),)
        base = base + self._sampler_args()
        res = self._decode_fn(*self._kv_args(
            *(base + (self._k_pools, self._v_pools))))
        # adopt the launch's pool lineage NOW: any prefill issued before
        # the commit must consume THESE outputs, so XLA's dataflow
        # orders the speculative KV write before the reuse
        self._k_pools, self._v_pools = res[4], res[5]
        if self._kv_quantized:
            self._k_scales, self._v_scales = res[6], res[7]
        self._feedback = res[0]
        records = []
        for req, slot in plan:
            req.cached_len += 1
            self._lens[slot] = req.cached_len
            if req.chew:
                t = req.chew.pop(0)
                self._tokens[slot] = t
                if req.cached_len < self.max_context:
                    self._history[slot, req.cached_len] = t
                self._override[slot] = True
            else:
                records.append((req, slot, req.cached_len,
                                req.admit_seq))
                self._override[slot] = False
        return {"res": res, "records": records}

    def _commit_inflight(self):
        """Commit the PREVIOUS launch: monitored wait, then stream its
        tokens through the normal emission path. A record whose request
        was cancelled / expired / preempted / finished since launch is
        discarded as `commit_lag_rollback` — boundary decisions land
        deterministically at lag 1, costing each departed stream exactly
        its one speculative token. Returns False when destructive
        recovery (hang rung 3 / decode fault) retired the batch."""
        from ..ops import guardian
        inf, self._inflight = self._inflight, None
        if inf is None:
            return True
        res = inf["res"]
        attempt = 1
        while True:
            try:
                self._monitor.wait(res, "decode", attempt)
                break
            except StepHang:
                self._stats.hangs += 1
                self._note_hang()
                _EVENTS.emit("serve.hang", "engine", reason="step_hang",
                             detail={"attempt": attempt,
                                     "phase": "commit",
                                     "active": len(
                                         self.scheduler.running)})
                consumed = self._pools_consumed()
                if attempt >= 2 or consumed:
                    # a wedged device holds BOTH outstanding launches —
                    # rungs 1-2 of the serial ladder cannot replay a
                    # window whose successor already consumed it, so the
                    # pipelined ladder goes straight to fail-active
                    self._degrade("step_hang",
                                  {"rung": "fail_active",
                                   "phase": "commit",
                                   "pools_consumed": consumed})
                    self._discard_records(inf)
                    for req in list(self.scheduler.running):
                        self._fail(req, "step_hang")
                    self._reset_pipeline()
                    if consumed:
                        self._reset_kv_state()
                    self._compile_grace_ns = time.perf_counter_ns()
                    self._decode_fn = self._build_decode(use_aot=False)
                    return False
                self._degrade("step_hang", {"rung": "retry",
                                            "phase": "commit"})
                attempt += 1
            except jax.errors.JaxRuntimeError as e:
                self._degrade("decode_fault",
                              {"organic": True, "error": str(e)[:200]})
                self._discard_records(inf)
                self._reset_pipeline()
                self._recover_with_fallback(rebuild=True)
                return False
        if guardian.poll_fault("serve.decode",
                               ("nan_output", "raise")) is not None:
            self._degrade("decode_fault", {"injected": True})
            self._discard_records(inf)
            self._reset_pipeline()
            self._recover_with_fallback(rebuild=False)
            return False
        toks = np.asarray(res[0])
        logps = np.asarray(res[1])
        aids = np.asarray(res[2])
        alps = np.asarray(res[3])
        for req, slot, pos, aseq in inf["records"]:
            if (req.state != RUNNING or req.slot != slot
                    or req.admit_seq != aseq):
                self._rollback(req, slot)
                continue
            tok = int(toks[slot])
            self._tokens[slot] = tok
            if pos < self.max_context:
                self._history[slot, pos] = tok
            self._emit_token(req, tok, logp=float(logps[slot]),
                             alts=((aids[slot], alps[slot])
                                   if self._logprobs_topk else None))
        self._maybe_store_decode()
        return True

    def _has_pending(self, req, slot):
        inf = self._inflight
        if inf is None:
            return False
        return any(r is req and s == slot
                   for r, s, _p, _a in inf["records"])

    def _discard_records(self, inf):
        for req, slot, _pos, _aseq in inf["records"]:
            self._rollback(req, slot)

    def _rollback(self, req, slot):
        """One speculative token discarded at the lag-1 boundary."""
        self._stats.commit_rollbacks += 1
        if _metrics_on():
            _M.commit_rollbacks.inc()
        _EVENTS.emit("serve.sample", req.rid,
                     reason="commit_lag_rollback",
                     detail={"slot": int(slot), "state": req.state})

    def _flush_inflight(self):
        """Synchronously commit (or roll back) the pending pipelined
        launch. Drain points — an idle boundary, the weight-swap
        cutover, explicit drains — must not leave a speculative token in
        flight. After the flush the host token mirror is authoritative
        for every slot. No-op when nothing is pending (including the
        unpipelined engine)."""
        if self._inflight is not None:
            self._commit_inflight()
        self._feedback = None
        self._override[:] = True

    def _reset_pipeline(self):
        self._inflight = None
        self._feedback = None
        self._override[:] = True

    def run(self, max_steps=None):
        """Drive step() until every request drains (or `max_steps`)."""
        n = 0
        while self.step():
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        return n

    def generate(self, prompts, max_new_tokens=16, eos_token_id=None):
        """Batch convenience: enqueue every prompt, run to drain, return
        the generated token lists (continuous batching under the hood —
        prompts of different lengths share slots and the block pool)."""
        reqs = [self.add_request(p, max_new_tokens,
                                 eos_token_id=eos_token_id)
                for p in prompts]
        self.run()
        for r in reqs:
            if r.state in (FAILED, EXPIRED, CANCELLED):
                raise RuntimeError(f"request {r.rid} failed: {r.error}")
        return [list(r.generated) for r in reqs]

    def stats(self):
        snap = self._stats.snapshot()
        snap["scheduler"] = self.scheduler.info()
        snap["kv_blocks"] = self.cache.num_blocks
        snap["block_size"] = self.block_size
        snap["attention_kernel"] = self._attn_kernel
        snap["kv_dtype"] = str(jnp.dtype(self._kv_dtype))
        if self._prefix is not None:
            snap["prefix_entries"] = self._prefix.entries
        if self._tenant:
            snap["weight_epoch"] = self._weight_epoch
            snap["adapters"] = ([] if self._adapters is None
                                else self._adapters.names())
        return snap

    def reset_stats(self):
        """Start a fresh measurement window (counters AND step-time
        samples); the compiled programs and the KV pool are untouched, so
        a post-warmup window sees decode_compiles == 0 unless something
        actually retraced."""
        self._stats.reset()

    def pop_finished(self):
        """Drain terminal request handles (FINISHED/FAILED/CANCELLED/
        EXPIRED) from the id registry and return them as {rid: Request}.
        A long-running server calls this after collecting results so the
        registry stays O(live); drained ids become reusable, exactly as
        if the handle had been overwritten."""
        done = {rid: r for rid, r in self.requests.items() if r.finished}
        for rid in done:
            del self.requests[rid]
        return done

    # ------------------------------------------------------------------
    # admission / prefill
    # ------------------------------------------------------------------
    @staticmethod
    def _bucket_for(n):
        return max(_MIN_BUCKET, 1 << (int(n - 1)).bit_length())

    def _admit(self, req):
        """Bucketed prefill of prompt + already-generated tokens (resume
        case) into the request's freshly assigned blocks, then join the
        decode batch. Never touches the decode executable. A prefix-hit
        admission (try_admit aliased cached blocks) skips the prefill
        entirely."""
        ctx = req.prompt + req.generated
        if req.prefix_hit > 0:
            self._admit_prefix_hit(req, ctx)
            return
        if self._prefix is not None:
            self._stats.prefix_prompt_tokens += len(ctx)
            self._note_prefix_rate()
            _EVENTS.emit("serve.prefix_miss", req.rid,
                         detail={"context_len": len(ctx)})
        bucket = self._bucket_for(len(ctx))
        fn = self._prefill_fns.get(bucket)
        new_bucket = fn is None
        if new_bucket:
            # the XLA trace runs on this bucket's FIRST call below —
            # grace the liveness window for it
            self._compile_grace_ns = time.perf_counter_ns()
            fn = self._build_prefill(bucket)
            self._prefill_fns[bucket] = fn
        self._stats.admitted += 1
        self._stats.prefills += 1
        _EVENTS.emit("serve.admit", req.rid,
                     reason="bucket_retrace" if new_bucket else None,
                     detail={"context_len": len(ctx), "bucket": bucket,
                             "blocks": len(req.blocks),
                             "resumed": bool(req.generated)})
        now = time.perf_counter_ns()
        if req.admit_ns is None:
            req.admit_ns = now
            wait_s = (now - req.enqueue_ns) / 1e9
            self._stats.queue_wait_hist.observe(wait_s)
            if _metrics_on():
                _M.queue_wait_s.observe(wait_s)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(ctx)] = ctx
        row = np.zeros(self.max_blocks_per_seq, np.int32)
        row[:len(req.blocks)] = req.blocks
        res = self._prefill_step(fn, padded, np.int32(len(ctx)), row, req)
        if res is None:
            return            # watchdog failed the request, slot is clear
        nxt, logp, aids, alps = res[0], res[1], res[2], res[3]
        self._k_pools, self._v_pools = res[4], res[5]
        if self._kv_quantized:
            self._k_scales, self._v_scales = res[6], res[7]
        req.cached_len = len(ctx)
        self._sync_slot(req)
        self._set_adapter_slot(req)
        if self._prefix is not None:
            # index this prompt's blocks for the NEXT tenant sharing it;
            # a resume's partial tail holds generated-token KV, which
            # must never be served as prompt KV
            self._prefix.publish(ctx, req.blocks,
                                 include_tail=not req.generated)
        tok = int(np.asarray(nxt))
        # the prefill's sampled token is the next decode step's input
        self._tokens[req.slot] = tok
        if req.cached_len < self.max_context:
            self._history[req.slot, req.cached_len] = tok
        self._override[req.slot] = True
        self._emit_token(req, tok, logp=float(np.asarray(logp)),
                         alts=((aids, alps) if self._logprobs_topk
                               else None))

    def _admit_prefix_hit(self, req, ctx):
        """Prefix-hit admission: the aliased blocks already hold the
        first `prefix_hit` tokens' KV, so there is NO prefill — the
        stream joins the decode batch at `cached_len = hit` and the
        decode step chews the remaining known suffix tokens (one per
        iteration, nothing emitted) before real sampling resumes. N
        streams sharing a long system prompt pay its prefill — and its
        KV bytes — once."""
        hit = req.prefix_hit
        self._stats.admitted += 1
        self._stats.prefix_hit_tokens += hit
        self._stats.prefix_prompt_tokens += len(ctx)
        _EVENTS.emit("serve.admit", req.rid,
                     detail={"context_len": len(ctx), "bucket": None,
                             "blocks": len(req.blocks),
                             "resumed": bool(req.generated),
                             "prefix_hit": hit})
        _EVENTS.emit("serve.prefix_hit", req.rid, reason="prefix_hit",
                     detail={"hit_tokens": hit,
                             "context_len": len(ctx),
                             "chew": len(ctx) - hit - 1})
        now = time.perf_counter_ns()
        if req.admit_ns is None:
            req.admit_ns = now
            wait_s = (now - req.enqueue_ns) / 1e9
            self._stats.queue_wait_hist.observe(wait_s)
            if _metrics_on():
                _M.queue_wait_s.observe(wait_s)
        if _metrics_on():
            _M.prefix_hit_tokens.inc(hit)
        self._note_prefix_rate()
        req.cached_len = hit
        self._sync_slot(req)
        self._set_adapter_slot(req)
        # decode input: the first token WITHOUT cached KV; the known
        # tokens after it queue as chew (fed, never emitted)
        self._tokens[req.slot] = int(ctx[hit])
        self._override[req.slot] = True
        req.chew = [int(t) for t in ctx[hit + 1:]]

    def _note_prefix_rate(self):
        if _metrics_on() and self._stats.prefix_prompt_tokens:
            _M.prefix_hit_rate.set(self._stats.prefix_hit_tokens
                                   / self._stats.prefix_prompt_tokens)

    def _set_adapter_slot(self, req):
        """Point the request's batch slot at its tenant's adapter stack
        index (0 = base). An index CHANGE is an adapter switch — the
        churn the zero-retrace contract is measured against."""
        if self._adapters is None:
            return
        idx = self._adapters.slot_of(req.adapter)
        if idx != int(self._aslots[req.slot]):
            self._stats.adapter_switches += 1
            if _metrics_on():
                _M.adapter_switches.inc()
        self._aslots[req.slot] = idx

    def _prefill_step(self, fn, padded, length, row, req):
        """One monitored prefill fire. The ladder is per-request (a hung
        prefill only has one tenant): retry once, then fail the request
        with `step_hang` — the decode batch never waits on it."""
        attempt = 1
        while True:
            try:
                base = (padded, length, row)
                if self._tenant:
                    base = base + (self._prefill_aux(req),)
                # the admitted request's sampler config rides as scalar
                # VALUES — a new config never re-keys the bucket program
                base = base + (np.float32(req.temperature),
                               np.int32(req.top_k),
                               np.float32(req.top_p),
                               np.float32(req.repetition_penalty),
                               np.uint32(req.seed or 0))
                res = fn(*self._kv_args(*(base + (self._k_pools,
                                                  self._v_pools))))
                self._monitor.wait(res, "prefill", attempt)
                return res
            except StepHang:
                self._stats.hangs += 1
                self._note_hang()
                if _metrics_on():
                    # prefill time is not measured as a productive step,
                    # so there is no later interval to subtract from
                    _goodput.ACCOUNTANT.drop_stall_carry()
                _EVENTS.emit("serve.hang", req.rid, reason="step_hang",
                             detail={"phase": "prefill",
                                     "attempt": attempt})
                consumed = self._pools_consumed()
                if attempt >= 2 or consumed:
                    self._degrade("step_hang",
                                  {"rung": "fail_request",
                                   "phase": "prefill", "rid": req.rid,
                                   "pools_consumed": consumed})
                    self._fail(req, "step_hang")
                    if consumed:
                        surviving = list(self.scheduler.running)
                        for r in surviving:
                            # their KV lived in the consumed pools
                            self._evict(r)
                        self._reset_kv_state()
                    return None
                self._degrade("step_hang", {"rung": "retry",
                                            "phase": "prefill"})
                attempt += 1

    def _kv_args(self, *base):
        """Positional args for the compiled decode/prefill programs:
        `base` plus the int8 scale side-tables when the pool is
        quantized — the single source of truth for the signatures'
        optional trailing pair."""
        if self._kv_quantized:
            return base + (self._k_scales, self._v_scales)
        return base

    def _sync_slot(self, req):
        slot = req.slot
        row = np.zeros(self.max_blocks_per_seq, np.int32)
        row[:len(req.blocks)] = req.blocks
        self._tables[slot] = row
        self._lens[slot] = req.cached_len
        self._active[slot] = True
        self._temps[slot] = req.temperature
        self._topks[slot] = req.top_k
        self._topps[slot] = req.top_p
        self._rpens[slot] = req.repetition_penalty
        self._seeds[slot] = req.seed or 0
        # rebuild the slot's context history from the COMMITTED tokens;
        # the in-graph scatter at index `lens` covers the one token a
        # pipelined launch knows only on-device
        ctx = req.prompt + req.generated
        self._history[slot] = 0
        n = min(len(ctx), self.max_context)
        self._history[slot, :n] = ctx[:n]

    def _clear_slot(self, slot):
        self._tables[slot] = 0
        self._lens[slot] = 0
        self._active[slot] = False
        self._tokens[slot] = 0
        # sampler no-op values keep a cleared slot on the all-greedy
        # cond branch (and out of the repetition-penalty seen set)
        self._temps[slot] = 0.0
        self._topks[slot] = 0
        self._topps[slot] = 1.0
        self._rpens[slot] = 1.0
        self._seeds[slot] = 0
        self._history[slot] = 0
        self._override[slot] = True

    # ------------------------------------------------------------------
    # token delivery / retirement
    # ------------------------------------------------------------------
    def _emit_token(self, req, tok, logp=None, alts=None):
        req.generated.append(tok)
        # logprob panels stay index-aligned with `generated`: None for
        # tokens whose emitting step's outputs no longer exist (prefix
        # chew, crash resume, eager fallback)
        req.token_logprobs.append(logp)
        if alts is None:
            req.alt_ids.append(None)
            req.alt_logprobs.append(None)
        else:
            req.alt_ids.append([int(i) for i in np.asarray(alts[0])])
            req.alt_logprobs.append([float(v)
                                     for v in np.asarray(alts[1])])
        self._stats.tokens_generated += 1
        if req.temperature > 0:
            self._stats.sampled_tokens += 1
            if _metrics_on():
                _M.sampled_tokens.inc()
        now = time.perf_counter_ns()
        mon = _metrics_on()
        if req.first_token_ns is None:
            req.first_token_ns = now
            ttft_s = (now - req.enqueue_ns) / 1e9
            self._stats.ttft_hist.observe(ttft_s)
            if mon:
                _M.ttft_s.observe(ttft_s)
        elif req.last_token_ns is not None:
            gap_s = (now - req.last_token_ns) / 1e9
            self._stats.inter_token_hist.observe(gap_s)
            if mon:
                _M.inter_token_s.observe(gap_s)
        req.last_token_ns = now
        req.token_ns.append(now)
        if mon:
            _M.tokens.inc()
        if req.on_token is not None:
            text = None
            if self._tokenizer is not None:
                try:
                    text = self._tokenizer.decode([tok])
                except Exception:
                    text = None
            req.on_token(req, tok, text)
        done = len(req.generated) >= req.max_new_tokens
        if req.eos_token_id is not None and tok == req.eos_token_id:
            done = True
        if done:
            self._finish(req)

    def _finish(self, req):
        slot = req.slot
        self.scheduler.release(req)
        if slot is not None:
            self._clear_slot(slot)
        req.state = FINISHED
        req.finish_ns = time.perf_counter_ns()
        self._stats.completed += 1
        if _metrics_on():
            _M.requests.labels(outcome="completed").inc()
        _EVENTS.emit("serve.complete", req.rid,
                     detail={"tokens": len(req.generated),
                             "preemptions": req.preemptions})

    def _fail(self, req, why):
        slot = req.slot
        self.scheduler.release(req)
        if slot is not None:
            self._clear_slot(slot)
        req.state = FAILED
        req.error = why
        req.finish_ns = time.perf_counter_ns()
        self._stats.failed += 1
        if _metrics_on():
            _M.requests.labels(outcome="failed").inc()
        _EVENTS.emit("serve.complete", req.rid, reason=why,
                     detail={"failed": True,
                             "tokens": len(req.generated)})

    def _evict(self, victim):
        """Preempt-resume: forget the victim's KV (a block-table edit),
        requeue at its arrival position; resume re-prefills."""
        slot = victim.slot
        self._stats.evictions += 1
        if _metrics_on():
            _M.preemptions.inc()
        _EVENTS.emit("serve.evict", victim.rid, reason="kv_exhausted",
                     detail={"freed_blocks": len(victim.blocks),
                             "cached_tokens": victim.cached_len,
                             "preemptions": victim.preemptions + 1})
        self.scheduler.preempt(victim)
        if slot is not None:
            self._clear_slot(slot)

    # ------------------------------------------------------------------
    # watchdog + degraded-mode recovery (serving/resilience.py)
    # ------------------------------------------------------------------
    def _decode_step(self):
        """Run the compiled decode step through the monitored completion.
        Returns the next-token array, or None when the recovery ladder
        retired the running batch (hang rung 3 / decode-fault eager
        fallback) — the engine keeps serving queued and new requests
        either way."""
        from ..ops import guardian
        if self._decode_fn is None:
            self._compile_grace_ns = time.perf_counter_ns()
            self._decode_fn = self._build_decode()
        attempt = 1
        while True:
            try:
                base = (self._tokens, self._tables, self._lens,
                        self._active)
                if self._tenant:
                    base = base + (self._decode_aux(),)
                base = base + self._sampler_args()
                res = self._decode_fn(*self._kv_args(
                    *(base + (self._k_pools, self._v_pools))))
                self._monitor.wait(res, "decode", attempt)
            except StepHang:
                if not self._on_hang(attempt):
                    return None
                attempt += 1
                continue
            except jax.errors.JaxRuntimeError as e:
                # organic execution fault: the program/device state is
                # suspect — eager-finish the batch, rebuild the program
                self._degrade("decode_fault",
                              {"organic": True, "error": str(e)[:200]})
                self._recover_with_fallback(rebuild=True)
                return None
            nxt = res[0]
            if guardian.poll_fault("serve.decode",
                                   ("nan_output", "raise")) is not None:
                # chaos-poisoned fused decode output: commit NOTHING from
                # this launch; the in-flight streams finish through the
                # eager path token-identically. The executable itself is
                # healthy (the poison models a transient device fault),
                # so no rebuild — decode still compiles exactly once.
                self._degrade("decode_fault", {"injected": True})
                self._recover_with_fallback(rebuild=False)
                return None
            self._k_pools, self._v_pools = res[4], res[5]
            if self._kv_quantized:
                self._k_scales, self._v_scales = res[6], res[7]
            self._maybe_store_decode()
            return (np.asarray(nxt), np.asarray(res[1]),
                    np.asarray(res[2]), np.asarray(res[3]))

    def _sampler_args(self):
        """The decode signature's per-slot sampler VALUE inputs, in
        positional order — the single source of truth shared by the live
        call, the AOT spec builder, and the pipelined launch."""
        return (self._temps, self._topks, self._topps, self._rpens,
                self._seeds, self._history)

    def _pools_consumed(self):
        deleted = getattr(self._k_pools, "is_deleted", None)
        if deleted is not None and deleted():
            return True
        deleted = getattr(self._v_pools, "is_deleted", None)
        return deleted is not None and deleted()

    def _note_hang(self):
        """Metrics-side view of one watchdog firing: the wedged wall
        time (the armed budget the monitor just burned) lands in the
        goodput `stalled` bucket and the hang counter."""
        if not _metrics_on():
            return
        _M.hangs.inc()
        budget_s = float(_FLAGS.get("FLAGS_serve_step_timeout_ms")
                         or 0) / 1e3
        if budget_s > 0:
            # the stalled decode step is the one ABOUT to commit — its
            # index lands in the goodput attribution ring so /goodput
            # and the doctor can say WHICH steps stalled
            _goodput.ACCOUNTANT.note_stall(budget_s, kind="step_hang",
                                           step=self._stats.steps + 1)

    def _degrade(self, reason, detail):
        """Enter (or deepen) degraded mode with an attributed
        transition."""
        self.degraded = True
        _EVENTS.emit("serve.degrade", "engine", reason=reason,
                     detail=detail)

    def _on_hang(self, attempt):
        """One watchdog firing: attribute it, climb the recovery ladder.
        Returns True to retry the step (rungs 1-2), False after rung 3
        (active requests failed, engine reset for new work)."""
        self._stats.hangs += 1
        self._note_hang()
        _EVENTS.emit("serve.hang", "engine", reason="step_hang",
                     detail={"attempt": attempt,
                             "active": len(self.scheduler.running)})
        consumed = self._pools_consumed()
        if consumed or attempt >= 3:
            # rung 3: the step would not come back (or its donated
            # buffers are gone) — fail the batch with an attributed
            # reason instead of wedging, and restore serviceability
            self._degrade("step_hang", {"rung": "fail_active",
                                        "pools_consumed": consumed})
            for req in list(self.scheduler.running):
                self._fail(req, "step_hang")
            if consumed:
                self._reset_kv_state()
            self._compile_grace_ns = time.perf_counter_ns()
            self._decode_fn = self._build_decode(use_aot=False)
            return False
        if attempt == 1:
            # rung 1: transient host/device hiccup — retry the same
            # executable with the same inputs
            self._degrade("step_hang", {"rung": "retry"})
        else:
            # rung 2: the executable itself is suspect — rebuild it
            # (the retrace is honest: decode_compiles counts it, the
            # degrade event explains it)
            self._degrade("step_hang", {"rung": "rebuild"})
            self._compile_grace_ns = time.perf_counter_ns()
            self._decode_fn = self._build_decode(use_aot=False)
        return True

    def _recover_with_fallback(self, rebuild):
        """Degraded-mode fallback: finish every running stream through
        the model's own eager `generate()` (token-identical to the
        compiled decode per the PR 6 parity contract), then restore the
        compiled path for queued/new requests."""
        for req in list(self.scheduler.running):
            self._fallback_eager(req)
        if self._pools_consumed():
            self._reset_kv_state()
        if rebuild:
            self._compile_grace_ns = time.perf_counter_ns()
            self._decode_fn = self._build_decode(use_aot=False)

    def _fallback_eager(self, req):
        """Finish one request via model.generate() from its prompt +
        emitted tokens; streams through the same on_token path."""
        self._stats.eager_fallbacks += 1
        _EVENTS.emit("serve.degrade", req.rid, reason="decode_fault",
                     detail={"fallback": "eager_generate",
                             "remaining": req.remaining_tokens})
        remaining = req.remaining_tokens
        if remaining > 0:
            ctx = np.asarray([req.prompt + req.generated], np.int64)
            if self._adapters is not None and req.adapter is not None:
                # the eager path folds the tenant's delta into the
                # weights (values only — generate's cached program does
                # not retrace) so the fallback serves the SAME model
                with self._adapters.merged(req.adapter):
                    out = self._model.generate(
                        ctx, max_new_tokens=remaining, do_sample=False)
            else:
                out = self._model.generate(ctx, max_new_tokens=remaining,
                                           do_sample=False)
            arr = np.asarray(out._value if hasattr(out, "_value")
                             else out)[0]
            for tok in arr.tolist():
                if req.finished:
                    break
                self._emit_token(req, int(tok))
        if not req.finished:
            self._finish(req)

    def _reset_kv_state(self):
        """Fresh block pool + slot arrays after a launch consumed or
        poisoned the KV buffers. Only legal with an empty running batch
        (callers retire it first); queued requests hold no blocks and
        re-prefill on admission."""
        assert not self.scheduler.running, \
            "KV reset with live streams would corrupt them"
        cfg = self._model.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.cache = PagedKVCache(cfg.num_hidden_layers,
                                  cfg.num_attention_heads, head_dim,
                                  self._num_blocks, self.block_size,
                                  self._kv_dtype)
        self.scheduler.allocator = self.cache.allocator
        s, m = self.max_batch_size, self.max_blocks_per_seq
        self._tables = np.zeros((s, m), np.int32)
        self._lens = np.zeros(s, np.int32)
        self._active = np.zeros(s, bool)
        self._tokens = np.zeros(s, np.int32)
        self._aslots = np.zeros(s, np.int32)
        self._temps = np.zeros(s, np.float32)
        self._topks = np.zeros(s, np.int32)
        self._topps = np.ones(s, np.float32)
        self._rpens = np.ones(s, np.float32)
        self._seeds = np.zeros(s, np.uint32)
        self._history = np.zeros((s, self.max_context), np.int32)
        self._inflight = None
        self._feedback = None
        self._override = np.ones(s, bool)
        self._k_pools = self.cache.k_pools
        self._v_pools = self.cache.v_pools
        self._k_scales = self.cache.k_scales
        self._v_scales = self.cache.v_scales
        if self._prefix is not None:
            # the old pool died with its allocator — the index's
            # references are meaningless now: forget, do not free
            self._prefix.reset(self.cache.allocator)

    # ------------------------------------------------------------------
    # crash-resume (serving/resilience.py + incubate.ServeCheckpointer)
    # ------------------------------------------------------------------
    def state_payload(self):
        """JSON-able snapshot of every in-flight request (prompt, emitted
        tokens, arrival order, remaining TTL) — NOT the KV pool, which
        re-prefills token-identically on resume. Saved each boundary by
        `incubate.checkpoint.ServeCheckpointer`; feed the loaded payload
        to `restore_state()` in the restarted process."""
        now = time.perf_counter_ns()
        # waiting + running IS the live set — O(live) per snapshot, so
        # the tick-every-step ServeCheckpointer pattern stays affordable
        # on a long-running server (the id registry may hold terminal
        # handles until pop_finished() drains them)
        live = sorted(list(self.scheduler.waiting)
                      + list(self.scheduler.running),
                      key=lambda r: (r.arrival_seq
                                     if r.arrival_seq is not None else -1))
        payload = {"version": 1, "kind": "serve_state",
                   "next_rid": self._next_rid,
                   "requests": [request_payload(r, now) for r in live]}
        if self._tenant:
            # the restore-time torn-swap check keys on these: a snapshot
            # taken under one weight epoch must not resume under another
            payload["weight_epoch"] = self._weight_epoch
            payload["weights_crc"] = self._weights_crc
            payload["swap_pending"] = self._pending_weights is not None
            payload["adapters"] = ([] if self._adapters is None
                                   else self._adapters.names())
        return payload

    def restore_state(self, payload, on_token=None):
        """Re-admit every request of a `state_payload()` snapshot in its
        original arrival order. Each resumes as QUEUED with its emitted
        tokens intact — first admission re-prefills prompt + generated
        and the stream continues byte-identically. `on_token` (callbacks
        never serialize): None, one callable for every request, or a
        {request_id: callable} mapping. Returns the restored Requests."""
        if not payload:
            return []
        crc = payload.get("weights_crc")
        if self._hot_swap and crc is not None \
                and crc != self._weights_crc:
            # torn swap: the snapshot was taken under a different weight
            # set than the one this process loaded — resuming would
            # decode half of every stream under each epoch. Refuse; the
            # supervisor loads the matching checkpoint and retries.
            _EVENTS.emit("serve.refuse", "engine", reason="torn_swap",
                         detail={"payload_crc": crc,
                                 "engine_crc": self._weights_crc,
                                 "payload_epoch":
                                     payload.get("weight_epoch"),
                                 "swap_pending":
                                     payload.get("swap_pending")})
            if _metrics_on():
                _M.refusals.labels(reason="torn_swap").inc()
            raise ServeRefusal(
                "torn_swap",
                f"state snapshot was taken under weights_crc {crc:#x} "
                f"but this engine serves {self._weights_crc:#x}; load "
                "the matching weight set before restoring",
                {"payload_crc": crc, "engine_crc": self._weights_crc})
        restored = []
        for rp in sorted(payload.get("requests", ()),
                         key=lambda p: p.get("arrival_seq") or 0):
            rid = rp["rid"]
            ad = rp.get("adapter")
            if ad is not None and (
                    self._adapters is None
                    or not self._adapters.is_registered(ad)):
                _EVENTS.emit("serve.refuse", rid,
                             reason="adapter_mismatch",
                             detail={"adapter": ad, "resume": True})
                if _metrics_on():
                    _M.refusals.labels(reason="adapter_mismatch").inc()
                raise ServeRefusal(
                    "adapter_mismatch",
                    f"restore_state: request {rid!r} decodes under "
                    f"adapter {ad!r}, which is not registered in this "
                    "engine; re-register every tenant before restoring",
                    {"rid": rid, "adapter": ad})
            prev = self.requests.get(rid)
            if prev is not None and not prev.finished:
                raise ValueError(
                    f"restore_state: request id {rid!r} is already live "
                    "in this engine")
            cb = (on_token.get(rid) if isinstance(on_token, dict)
                  else on_token)
            req = payload_request(rp, cb)
            self.scheduler.enqueue(req)
            self.requests[rid] = req
            self._stats.resumed += 1
            _EVENTS.emit("serve.resume", rid, reason="crash_resume",
                         detail={"generated": len(req.generated),
                                 "remaining": req.remaining_tokens})
            restored.append(req)
        self._next_rid = max(self._next_rid,
                             int(payload.get("next_rid") or 0))
        self._weight_epoch = max(self._weight_epoch,
                                 int(payload.get("weight_epoch") or 0))
        return restored

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _donate(self, argnums):
        # CPU ignores buffer donation (with a warning per program) —
        # only request it where it is real
        return argnums if jax.default_backend() != "cpu" else ()

    def _aot_decode_digest(self):
        """Content address of the decode executable: model class + config
        + slot/pool geometry + a CRC over the weights, so a fine-tune or a
        resized pool re-keys instead of replaying stale math. Computed
        once per engine (the CRC walk is O(bytes), paid only with
        FLAGS_aot_cache on)."""
        if self._aot_digest_cache is not None:
            return self._aot_digest_cache or None
        from ..ops import aot_cache as _aot
        import zlib
        try:
            crc = 0
            if not self._hot_swap:
                # hot-swap mode passes the weights as VALUES — they are
                # not baked into the executable, so they must not key it
                for p in self._model.parameters():
                    v = np.asarray(p._value)
                    crc = zlib.crc32(
                        repr((v.shape, str(v.dtype))).encode(), crc)
                    crc = zlib.crc32(v.tobytes(), crc)
            cfg = {k: v for k, v in vars(self._model.config).items()
                   if isinstance(v, (int, float, bool, str, type(None)))}
            # tenant mode re-keys the artifact: the aux-input signature
            # (weights as values, adapter stack rank/shape) is a
            # different program from the baked-weights one
            tenant = (self._tenant, self._hot_swap,
                      0 if self._adapters is None
                      else (self._adapters.max_adapters,
                            self._adapters.rank))
            dg = _aot._digest_of(
                ("decode", type(self._model).__qualname__,
                 tuple(sorted(cfg.items())), self.max_batch_size,
                 self.block_size, self._num_blocks,
                 self.max_blocks_per_seq, str(self._dtype),
                 # the kernel tier re-keys the artifact: a blockwise
                 # executable must never replay as the pallas one, and an
                 # int8 pool has a different signature entirely
                 self._attn_kernel, str(jnp.dtype(self._kv_dtype)), crc,
                 tenant,
                 # the sampler head is part of the program: its math
                 # version, the static logprob panel width and the
                 # history buffer width all change the executable
                 ("sampler", SAMPLER_VERSION, self._logprobs_topk,
                  self.max_context)))
        except Exception:
            dg = None
        self._aot_digest_cache = dg or ""
        return dg

    def _maybe_store_decode(self):
        """Persist the decode executable after its first successful step
        (the export re-traces `decode`, honestly counted by
        decode_compiles — paid once, only in storing processes)."""
        pending, self._aot_pending_store = self._aot_pending_store, None
        if pending is None:
            return
        digest, jitted = pending
        from ..ops import aot_cache as _aot
        if not _aot.enabled() or _aot.has_artifact("decode", digest):
            return
        try:
            specs = tuple(_aot._spec_of(a) for a in self._kv_args(
                self._tokens, self._tables, self._lens, self._active,
                self._temps, self._topks, self._topps, self._rpens,
                self._seeds, self._history,
                self._k_pools, self._v_pools))
            blobs = [_aot.export_bytes(jitted, specs)]
        except Exception as e:
            from ..profiler.aot import STATS as _ASTATS
            _ASTATS.store_failures += 1
            _EVENTS.emit("aot.store", "serve.decode",
                         detail={"kind": "decode",
                                 "failed": repr(e)[:200]})
            return
        _aot.store_artifact("decode", digest, "serve.decode", blobs,
                            meta={"max_batch_size": self.max_batch_size,
                                  "block_size": self.block_size})

    def _build_decode(self, use_aot=True):
        if self._tenant:
            # the aux-input program: weights/adapters as values. AOT
            # export of a pytree-carrying signature is not supported —
            # tenant replicas always trace once at start
            return self._build_decode_tenant()
        model = self._model
        num_layers = model.config.num_hidden_layers
        block_size = self.block_size
        stats = self._stats
        variant = self._attn_kernel
        lp_topk = self._logprobs_topk

        def decode(tokens, tables, lens, active, temps, topks, topps,
                   rpens, seeds, history, k_pools, v_pools,
                   k_scales=None, v_scales=None):
            stats.decode_compiles += 1   # runs only while tracing
            views = [PagedCacheView(
                k_pools[l], v_pools[l], tables, lens, active, block_size,
                k_scales=None if k_scales is None else k_scales[l],
                v_scales=None if v_scales is None else v_scales[l],
                kernel=variant)
                for l in range(num_layers)]
            with set_grad_enabled(False):
                logits, new_views = model(
                    Tensor(tokens[:, None], stop_gradient=True),
                    caches=views)
            new_k = jnp.stack([v.k_pool for v in new_views])
            new_v = jnp.stack([v.v_pool for v in new_views])
            # the in-graph history scatter: the input token enters the
            # context at index `lens` — under pipelined decode it may
            # exist ONLY on-device (feedback), so the host mirror cannot
            # be trusted to contain it
            rows = jnp.arange(tokens.shape[0], dtype=jnp.int32)
            idx = jnp.clip(lens, 0, history.shape[1] - 1)
            hist = history.at[rows, idx].set(tokens)
            valid = (jnp.arange(history.shape[1], dtype=jnp.int32)[None, :]
                     <= lens[:, None])
            # sampling position = known context tokens = lens + 1; every
            # replay (preempt re-prefill, rebuild, kill-9 resume)
            # restores the same positions -> byte-identical streams
            nxt, logp, alt_ids, alt_lps = sample_tokens(
                logits._value[:, -1, :], temps, topks, topps, rpens,
                seeds, lens + 1, hist, valid, logprobs_topk=lp_topk)
            if k_scales is not None:
                new_ks = jnp.stack([v.k_scales for v in new_views])
                new_vs = jnp.stack([v.v_scales for v in new_views])
                return (nxt, logp, alt_ids, alt_lps, new_k, new_v,
                        new_ks, new_vs)
            return nxt, logp, alt_ids, alt_lps, new_k, new_v

        donate = (10, 11, 12, 13) if self._kv_quantized else (10, 11)
        jitted = jax.jit(decode, donate_argnums=self._donate(donate))
        from ..ops import aot_cache as _aot
        if use_aot and _aot.enabled():
            # warm start: a restarted replica deserializes yesterday's
            # decode program and serves its first token without a trace.
            # The watchdog's rebuild rungs pass use_aot=False — a suspect
            # program must be replaced by a FRESH trace, not by the very
            # bytes that may embody the fault
            digest = self._aot_decode_digest()
            if digest is not None:
                exe = _aot.load_callable(
                    "decode", digest, "serve.decode",
                    fallback=lambda: jitted,
                    donate_argnums=self._donate(donate))
                if exe is not None:
                    return exe
                self._aot_pending_store = (digest, jitted)
        return jitted

    def _build_decode_tenant(self):
        """The multi-tenant decode executable: same fixed slot layout,
        plus an `aux` pytree of VALUE inputs — the base weights
        (hot-swap mode: a swap writes new values, never retraces) and
        the padded adapter stacks with the per-slot adapter index
        (tenant churn is a value edit). Weight substitution uses the
        same save/swap/restore idiom as `model.generate`: for the
        duration of the trace the parameters' `_value`s ARE the traced
        inputs. Compiles exactly once per engine, like the base
        program."""
        model = self._model
        num_layers = model.config.num_hidden_layers
        block_size = self.block_size
        stats = self._stats
        variant = self._attn_kernel
        params = model.parameters()
        holder = self._holder
        lp_topk = self._logprobs_topk

        def decode(tokens, tables, lens, active, aux, temps, topks,
                   topps, rpens, seeds, history, k_pools, v_pools,
                   k_scales=None, v_scales=None):
            stats.decode_compiles += 1   # runs only while tracing
            pvals = aux.get("params")
            saved = None
            if pvals is not None:
                saved = [pp._value for pp in params]
                for pp, vv in zip(params, pvals):
                    pp._value = vv
            if holder is not None and "adapters" in aux:
                holder["active"] = AdapterSet.trace_ctx(
                    aux["adapters"], slots=aux["aslots"])
            try:
                views = [PagedCacheView(
                    k_pools[l], v_pools[l], tables, lens, active,
                    block_size,
                    k_scales=None if k_scales is None else k_scales[l],
                    v_scales=None if v_scales is None else v_scales[l],
                    kernel=variant)
                    for l in range(num_layers)]
                with set_grad_enabled(False):
                    logits, new_views = model(
                        Tensor(tokens[:, None], stop_gradient=True),
                        caches=views)
            finally:
                if saved is not None:
                    for pp, vv in zip(params, saved):
                        pp._value = vv
                if holder is not None:
                    holder["active"] = None
            new_k = jnp.stack([v.k_pool for v in new_views])
            new_v = jnp.stack([v.v_pool for v in new_views])
            rows = jnp.arange(tokens.shape[0], dtype=jnp.int32)
            idx = jnp.clip(lens, 0, history.shape[1] - 1)
            hist = history.at[rows, idx].set(tokens)
            valid = (jnp.arange(history.shape[1], dtype=jnp.int32)[None, :]
                     <= lens[:, None])
            nxt, logp, alt_ids, alt_lps = sample_tokens(
                logits._value[:, -1, :], temps, topks, topps, rpens,
                seeds, lens + 1, hist, valid, logprobs_topk=lp_topk)
            if k_scales is not None:
                new_ks = jnp.stack([v.k_scales for v in new_views])
                new_vs = jnp.stack([v.v_scales for v in new_views])
                return (nxt, logp, alt_ids, alt_lps, new_k, new_v,
                        new_ks, new_vs)
            return nxt, logp, alt_ids, alt_lps, new_k, new_v

        donate = (11, 12, 13, 14) if self._kv_quantized else (11, 12)
        return jax.jit(decode, donate_argnums=self._donate(donate))

    def _build_prefill(self, bucket):
        if self._tenant:
            return self._build_prefill_tenant(bucket)
        model = self._model
        cfg = model.config
        num_layers = cfg.num_hidden_layers
        heads = cfg.num_attention_heads
        head_dim = cfg.hidden_size // heads
        block_size = self.block_size
        params = model.parameters()
        dt = params[0]._value.dtype if params else jnp.float32
        stats = self._stats
        lp_topk = self._logprobs_topk

        def prefill(ids, length, block_row, temp, topk, topp, rpen,
                    seedv, k_pools, v_pools,
                    k_scales=None, v_scales=None):
            stats.prefill_compiles += 1   # runs only while tracing
            empty = [(Tensor(jnp.zeros((1, 0, heads, head_dim), dt)),) * 2
                     for _ in range(num_layers)]
            with set_grad_enabled(False):
                logits, caches = model(Tensor(ids, stop_gradient=True),
                                       caches=[tuple(c) for c in empty])
            k_layers = jnp.stack([c[0]._value[0] for c in caches])
            v_layers = jnp.stack([c[1]._value[0] for c in caches])
            written = scatter_prefill(
                k_pools, v_pools, k_layers, v_layers, block_row, length,
                block_size, k_scales=k_scales, v_scales=v_scales)
            last = jax.lax.dynamic_index_in_dim(
                logits._value[0], length - 1, axis=0, keepdims=False)
            # the prompt's first sampled token: position = prompt length
            # (the count of known context tokens), same convention as the
            # decode head — replays land on the same fold_in stream
            valid = (jnp.arange(ids.shape[1], dtype=jnp.int32)
                     < length)[None, :]
            nxt, logp, alt_ids, alt_lps = sample_tokens(
                last[None, :], jnp.reshape(temp, (1,)),
                jnp.reshape(topk, (1,)), jnp.reshape(topp, (1,)),
                jnp.reshape(rpen, (1,)), jnp.reshape(seedv, (1,)),
                jnp.reshape(length, (1,)), ids.astype(jnp.int32), valid,
                logprobs_topk=lp_topk)
            return (nxt[0], logp[0], alt_ids[0], alt_lps[0]) \
                + tuple(written)

        donate = (8, 9, 10, 11) if self._kv_quantized else (8, 9)
        return jax.jit(prefill, donate_argnums=self._donate(donate))

    def _build_prefill_tenant(self, bucket):
        """Tenant twin of `_build_prefill`: the same bucketed prompt
        program with the aux pytree (weights as values in hot-swap mode;
        the one admitted request's scalar adapter slot)."""
        model = self._model
        cfg = model.config
        num_layers = cfg.num_hidden_layers
        heads = cfg.num_attention_heads
        head_dim = cfg.hidden_size // heads
        block_size = self.block_size
        params = model.parameters()
        dt = params[0]._value.dtype if params else jnp.float32
        stats = self._stats
        holder = self._holder
        lp_topk = self._logprobs_topk

        def prefill(ids, length, block_row, aux, temp, topk, topp, rpen,
                    seedv, k_pools, v_pools,
                    k_scales=None, v_scales=None):
            stats.prefill_compiles += 1   # runs only while tracing
            pvals = aux.get("params")
            saved = None
            if pvals is not None:
                saved = [pp._value for pp in params]
                for pp, vv in zip(params, pvals):
                    pp._value = vv
            if holder is not None and "adapters" in aux:
                holder["active"] = AdapterSet.trace_ctx(
                    aux["adapters"], slot=aux["slot"])
            try:
                empty = [(Tensor(jnp.zeros((1, 0, heads, head_dim),
                                           dt)),) * 2
                         for _ in range(num_layers)]
                with set_grad_enabled(False):
                    logits, caches = model(
                        Tensor(ids, stop_gradient=True),
                        caches=[tuple(c) for c in empty])
            finally:
                if saved is not None:
                    for pp, vv in zip(params, saved):
                        pp._value = vv
                if holder is not None:
                    holder["active"] = None
            k_layers = jnp.stack([c[0]._value[0] for c in caches])
            v_layers = jnp.stack([c[1]._value[0] for c in caches])
            written = scatter_prefill(
                k_pools, v_pools, k_layers, v_layers, block_row, length,
                block_size, k_scales=k_scales, v_scales=v_scales)
            last = jax.lax.dynamic_index_in_dim(
                logits._value[0], length - 1, axis=0, keepdims=False)
            valid = (jnp.arange(ids.shape[1], dtype=jnp.int32)
                     < length)[None, :]
            nxt, logp, alt_ids, alt_lps = sample_tokens(
                last[None, :], jnp.reshape(temp, (1,)),
                jnp.reshape(topk, (1,)), jnp.reshape(topp, (1,)),
                jnp.reshape(rpen, (1,)), jnp.reshape(seedv, (1,)),
                jnp.reshape(length, (1,)), ids.astype(jnp.int32), valid,
                logprobs_topk=lp_topk)
            return (nxt[0], logp[0], alt_ids[0], alt_lps[0]) \
                + tuple(written)

        donate = (9, 10, 11, 12) if self._kv_quantized else (9, 10)
        return jax.jit(prefill, donate_argnums=self._donate(donate))

    # ------------------------------------------------------------------
    # multi-tenant serving (PR 17, serving/tenancy.py)
    # ------------------------------------------------------------------
    def _decode_aux(self):
        """The decode executable's aux VALUE inputs — a pytree with a
        STABLE structure per engine config (keys never appear or vanish
        between calls), so churning its values never re-keys the
        program."""
        aux = {}
        if self._hot_swap:
            aux["params"] = [p._value
                             for p in self._model.parameters()]
        if self._adapters is not None:
            aux["adapters"] = self._adapters.device_stacks()
            aux["aslots"] = jnp.asarray(self._aslots)
        return aux

    def _prefill_aux(self, req):
        aux = {}
        if self._hot_swap:
            aux["params"] = [p._value
                             for p in self._model.parameters()]
        if self._adapters is not None:
            aux["adapters"] = self._adapters.device_stacks()
            aux["slot"] = jnp.asarray(
                self._adapters.slot_of(req.adapter), jnp.int32)
        return aux

    def _prefix_hook(self, req):
        """try_admit's shared-prefix acquisition: the longest cached
        block run matching the head's context, increfed for the
        admission. The scheduler undoes the claim symmetrically when
        admission fails anyway (watermark / pool pressure)."""
        return self._prefix.acquire(req.prompt + req.generated)

    def _reclaim_prefix(self, num_free_target):
        """Drop cold prefix-cache entries (leaf-first, LRU) until the
        allocator can serve `num_free_target` free blocks. Attribution
        happens HERE, after the cache released its lock (R6: no events
        under a lock). True when anything was freed."""
        dropped = self._prefix.reclaim(num_free_target)
        if not dropped:
            return False
        self._stats.prefix_evictions += dropped
        _EVENTS.emit("serve.prefix_evict", "engine",
                     detail={"entries": dropped,
                             "free_blocks":
                                 self.cache.allocator.num_free})
        return True

    def _cow_sweep(self):
        """Copy-on-write boundary: before the decode step writes each
        stream's next-token KV at position `cached_len`, any stream
        whose target block is still SHARED (refcount > 1 — a prefix
        entry and/or sibling streams also own it) gets a private copy:
        one jitted block copy, a host table edit, a decref of the
        original. The first divergent write therefore never clobbers KV
        another stream is attending over."""
        sched = self.scheduler
        alloc = self.cache.allocator
        for req in sorted(list(sched.running),
                          key=lambda r: r.admit_seq):
            if req.state != RUNNING:
                continue      # evicted/failed by an earlier COW's ladder
            wi = req.cached_len // self.block_size
            if wi >= len(req.blocks):
                continue
            src = req.blocks[wi]
            if alloc.refcount(src) <= 1:
                continue
            got = alloc.allocate(1)
            while got is None:
                # same pressure ladder as growth: cold prefix entries
                # first, then LIFO preemption, then give up on this one
                if self._reclaim_prefix(1):
                    got = alloc.allocate(1)
                    continue
                victim = sched.preempt_victim(exclude=req)
                if victim is None:
                    break
                self._evict(victim)
                got = alloc.allocate(1)
            if got is None:
                if not sched.protected(req):
                    self._evict(req)
                else:
                    self._fail(req, "kv_exhausted")
                continue
            dst = got[0]
            self._copy_block(src, dst)
            alloc.free([src])
            req.blocks[wi] = dst
            self._sync_slot(req)
            self._stats.cow_copies += 1

    def _copy_block(self, src, dst):
        """One jitted pool-to-pool block copy (all layers, K+V, and the
        int8 scale rows). src/dst are traced int32 scalars, so the copy
        program compiles once and serves every COW."""
        if self._cow_fn is None:
            def cow(k_pools, v_pools, src, dst,
                    k_scales=None, v_scales=None):
                k_pools = k_pools.at[:, dst].set(k_pools[:, src])
                v_pools = v_pools.at[:, dst].set(v_pools[:, src])
                if k_scales is not None:
                    k_scales = k_scales.at[:, dst].set(k_scales[:, src])
                    v_scales = v_scales.at[:, dst].set(v_scales[:, src])
                    return k_pools, v_pools, k_scales, v_scales
                return k_pools, v_pools

            donate = (0, 1, 4, 5) if self._kv_quantized else (0, 1)
            self._cow_fn = jax.jit(cow,
                                   donate_argnums=self._donate(donate))
        res = self._cow_fn(*self._kv_args(
            self._k_pools, self._v_pools,
            jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32)))
        self._k_pools, self._v_pools = res[0], res[1]
        if self._kv_quantized:
            self._k_scales, self._v_scales = res[2], res[3]

    def _params_crc(self):
        """CRC over every parameter's bytes — the weight-set identity
        the hot-swap cutover and the crash-resume torn-swap check key
        on."""
        import zlib
        crc = 0
        for p in self._model.parameters():
            crc = zlib.crc32(np.asarray(p._value).tobytes(), crc)
        return crc

    def register_adapter(self, name, weights=None, scale=1.0, seed=None):
        """Install a tenant's LoRA-style adapter into a free stack slot
        (a VALUE edit of the padded stacks — zero retraces). See
        `tenancy.AdapterSet.register` for the weights layout."""
        if self._adapters is None:
            raise ValueError(
                "engine was built with max_adapters=0; adapters need "
                "max_adapters > 0 at construction (the stack shapes are "
                "baked into the decode executable)")
        return self._adapters.register(name, weights=weights,
                                       scale=scale, seed=seed)

    def unregister_adapter(self, name):
        """Free a departed tenant's slot. Refuses while any live stream
        still decodes under the adapter — zeroing the slot mid-stream
        would silently cut those streams over to base weights."""
        if self._adapters is None:
            raise ValueError("engine was built with max_adapters=0")
        live = [r.rid for r in (list(self.scheduler.waiting)
                                + list(self.scheduler.running))
                if r.adapter == name]
        if live:
            raise ValueError(
                f"adapter {name!r} still serves live requests {live}; "
                "drain or cancel them first")
        return self._adapters.unregister(name)

    def stage_weights(self, values):
        """Stage a live weight hot-swap: `values` (one array per
        `model.parameters()` entry, same shapes) replaces the base
        weights at the next iteration boundary — a byte-exact cutover:
        every token of every stream is produced entirely under one
        weight set or the other, never a mix. Returns True when staged;
        False when the incoming set is byte-identical to the serving
        one (attributed as a skipped `serve.swap`)."""
        if not self._hot_swap:
            raise ValueError(
                "engine was built without hot_swap=True — its weights "
                "are baked into the compiled programs as constants")
        import zlib
        params = self._model.parameters()
        if len(values) != len(params):
            raise ValueError(
                f"stage_weights: got {len(values)} arrays for "
                f"{len(params)} parameters")
        vals, crc = [], 0
        for p, v in zip(params, values):
            arr = jnp.asarray(v).astype(p._value.dtype)
            if arr.shape != p._value.shape:
                raise ValueError(
                    f"stage_weights: shape {arr.shape} does not match "
                    f"parameter shape {p._value.shape}")
            vals.append(arr)
            crc = zlib.crc32(np.asarray(arr).tobytes(), crc)
        if crc == self._weights_crc and self._pending_weights is None:
            _EVENTS.emit("serve.swap", "engine",
                         detail={"skipped": True, "crc_match": True,
                                 "epoch": self._weight_epoch})
            return False
        self._pending_weights = (vals, crc)
        return True

    def swap_weights(self, values):
        """Stage + commit a hot-swap. Called between steps (the usual
        checkpoint-watcher pattern) the cutover happens immediately;
        called from inside a streaming callback mid-step it commits at
        the next iteration boundary. Returns the serving weight epoch
        after the call."""
        if self.stage_weights(values) and not self._stepping:
            self._commit_swap()
        return self._weight_epoch

    def _commit_swap(self):
        """The cutover: preempt every running stream (they re-prefill
        under the new weights and continue from their emitted tokens),
        invalidate the prefix index (cached KV is a function of the base
        weights), write the staged values into the parameters, bump the
        epoch. No compiled program is touched — the weights are VALUE
        inputs."""
        # a pipelined launch in flight was issued under the OLD weights:
        # commit its tokens before the preemption sweep discards them
        self._flush_inflight()
        values, crc = self._pending_weights
        self._pending_weights = None
        sched = self.scheduler
        preempted = 0
        for req in list(sched.running):
            # scheduler.preempt directly — NOT _evict: this is a planned
            # cutover, not kv pressure, and must not pollute the
            # kv_exhausted eviction attribution
            slot = req.slot
            sched.preempt(req)
            if slot is not None:
                self._clear_slot(slot)
            preempted += 1
        dropped = (self._prefix.invalidate()
                   if self._prefix is not None else 0)
        for p, v in zip(self._model.parameters(), values):
            p._value = v
        self._weight_epoch += 1
        self._weights_crc = crc
        self._stats.weight_swaps += 1
        if _metrics_on():
            _M.weight_swaps.inc()
        _EVENTS.emit("serve.swap", "engine",
                     detail={"epoch": self._weight_epoch,
                             "preempted": preempted,
                             "prefix_dropped": dropped})

    @property
    def weight_epoch(self):
        """Serving weight-set generation (0 = construction weights)."""
        return self._weight_epoch
