"""DLPack interop. Reference analog: paddle.utils.dlpack
(framework/dlpack_tensor.cc) — zero-copy tensor exchange with other
frameworks.

Modern convention: exchange objects implementing the __dlpack__ protocol
(torch tensors, numpy arrays, jax arrays all do) rather than raw capsules —
jax removed legacy capsule ingestion, so to_dlpack returns the protocol
object and from_dlpack accepts any protocol object.
"""
from __future__ import annotations

import jax

from ..framework.core import Tensor
from ..ops._helpers import ensure_tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a Tensor as a DLPack-protocol object (implements __dlpack__).

    Pass the result to torch.from_dlpack / np.from_dlpack / etc."""
    return ensure_tensor(x)._value


def from_dlpack(ext_tensor):
    """Import any __dlpack__-protocol object (torch/numpy/jax) as a Tensor."""
    if not hasattr(ext_tensor, "__dlpack__"):
        raise TypeError(
            "from_dlpack needs an object implementing __dlpack__ (raw "
            "PyCapsule ingestion was removed from jax); pass the tensor "
            "object itself, e.g. from_dlpack(torch_tensor)")
    arr = jax.dlpack.from_dlpack(ext_tensor)
    return Tensor(arr, stop_gradient=True)
