"""Post-install smoke check. Reference analog:
python/paddle/fluid/install_check.py run_check() — a tiny train (plus 2-GPU
DP when available) proving the install works end to end."""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    print("Running verify PaddleTPU program ...")
    paddle.seed(0)
    model = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(np.random.randint(0, 2, (8,)).astype(np.int64))
    losses = []
    for _ in range(3):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all(), losses

    n_dev = jax.device_count()
    if n_dev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()).reshape(n_dev), ("data",))
        arr = jax.device_put(np.ones((n_dev * 2, 4), np.float32),
                             NamedSharding(mesh, P("data")))
        out = model(paddle.Tensor(arr, stop_gradient=True))
        assert np.isfinite(np.asarray(out._value)).all()
        print(f"PaddleTPU works well on {n_dev} devices.")
    print(f"PaddleTPU works well on 1 {jax.devices()[0].platform} device.")
    print("PaddleTPU is installed successfully!")
