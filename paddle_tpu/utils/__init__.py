"""Utilities. Reference analog: python/paddle/utils/."""
from __future__ import annotations

__all__ = ["try_import", "unique_name", "deprecated", "run_check"]

import importlib
import itertools


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg:
            raise ImportError(err_msg)
        raise


class _UniqueNameGenerator:
    def __init__(self):
        self._counters = {}

    def generate(self, key="tmp"):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    __call__ = generate


unique_name = _UniqueNameGenerator()


def deprecated(since=None, update_to=None, reason=None):
    def deco(fn):
        return fn
    return deco


from .install_check import run_check  # noqa: F401,E402
from . import dlpack  # noqa: F401,E402
from . import cpp_extension  # noqa: F401,E402
