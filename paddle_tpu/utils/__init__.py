"""Utilities. Reference analog: python/paddle/utils/."""
from __future__ import annotations

__all__ = ["try_import", "unique_name", "deprecated", "run_check"]

import importlib
import itertools


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg:
            raise ImportError(err_msg)
        raise


class _UniqueNameGenerator:
    def __init__(self):
        self._counters = {}

    def generate(self, key="tmp"):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    __call__ = generate


unique_name = _UniqueNameGenerator()


def deprecated(since=None, update_to=None, reason=None):
    def deco(fn):
        return fn
    return deco


def run_check():
    """Post-install smoke test. Reference analog:
    python/paddle/fluid/install_check.py (tiny train incl. DP)."""
    import numpy as np
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.random.rand(4, 8).astype("float32"))
    linear = paddle.nn.Linear(8, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=linear.parameters())
    loss = paddle.nn.functional.mse_loss(
        linear(x), paddle.zeros([4, 2]))
    loss.backward()
    opt.step()
    print("paddle_tpu is installed successfully!")
    import jax
    print(f"devices: {jax.devices()}")
