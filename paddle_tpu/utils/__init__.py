"""Utilities. Reference analog: python/paddle/utils/."""
from __future__ import annotations

__all__ = ["try_import", "unique_name", "deprecated", "run_check"]

import importlib
import itertools


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg:
            raise ImportError(err_msg)
        raise


class _UniqueNameGenerator:
    def __init__(self):
        self._counters = {}

    def generate(self, key="tmp"):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    __call__ = generate


unique_name = _UniqueNameGenerator()


def deprecated(since=None, update_to=None, reason=None):
    def deco(fn):
        return fn
    return deco


from .install_check import run_check  # noqa: F401,E402
from . import dlpack  # noqa: F401,E402
from . import cpp_extension  # noqa: F401,E402


def require_version(min_version, max_version=None):
    """Check the installed framework version is within [min_version,
    max_version] (reference: fluid/framework.py:393). Raises on mismatch,
    returns None when satisfied."""
    from .. import __version__

    def parse(v):
        parts = []
        for tok in str(v).split("."):
            num = "".join(ch for ch in tok if ch.isdigit())
            parts.append(int(num) if num else 0)
        return tuple(parts + [0] * (4 - len(parts)))

    if not isinstance(min_version, str) or (
            max_version is not None and not isinstance(max_version, str)):
        raise TypeError("min_version/max_version must be str")
    cur = parse(__version__)
    if cur < parse(min_version):
        raise Exception(
            f"installed version {__version__} is lower than the required "
            f"minimum {min_version}")
    if max_version is not None and cur > parse(max_version):
        raise Exception(
            f"installed version {__version__} is higher than the required "
            f"maximum {max_version}")


__all__.append("require_version")
