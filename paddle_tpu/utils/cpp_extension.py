"""Runtime-compiled custom C++ ops.

Reference analog: python/paddle/utils/cpp_extension/ (setup :78, JIT load
:799) + framework/custom_operator.cc — users compile out-of-tree C++ ops
loaded at runtime.

TPU-native design: the device compute path is XLA, so custom C++ code runs as
HOST ops bridged through jax.pure_callback (the role the reference's custom
CPU kernels play). Contract: each exported function has the C signature

    extern "C" void NAME(const float* x, float* y, int64_t n);

computing y[i] from x[i] (elementwise, same shape). `load()` compiles with
g++ -O2 -fPIC -shared, binds via ctypes, and returns a module-like object
whose attributes are ops usable from eager or jit code. Host callbacks have
no autodiff rule, so the ops are NON-differentiable: inputs requiring grad
are rejected with a clear error (detach() first, as with the reference's
backward-less custom ops).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops._helpers import ensure_tensor, call_op

__all__ = ["load", "CppExtension"]


def _cache_dir():
    d = os.environ.get("PADDLE_TPU_EXT_DIR",
                       os.path.join(os.path.expanduser("~"),
                                    ".cache", "paddle_tpu", "extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name, sources, extra_cflags):
    srcs = [os.path.abspath(s) for s in sources]
    digest = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            digest.update(f.read())
    digest.update(" ".join(extra_cflags or []).encode())
    lib_path = os.path.join(_cache_dir(),
                            f"{name}_{digest.hexdigest()[:16]}.so")
    if not os.path.exists(lib_path):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               *(extra_cflags or []), *srcs, "-o", lib_path]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"cpp_extension: compile failed:\n{proc.stderr}")
    return lib_path


class _HostOp:
    """One exported C function as a paddle op (elementwise f32)."""

    def __init__(self, cfn, name):
        cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        cfn.restype = None
        self._cfn = cfn
        self._name = name

    def _host(self, v):
        x = np.ascontiguousarray(np.asarray(v, np.float32))
        y = np.empty_like(x)
        self._cfn(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  x.size)
        return y

    def __call__(self, x):
        from ..framework.autograd import is_grad_enabled
        x = ensure_tensor(x)
        if is_grad_enabled() and not x.stop_gradient:
            raise RuntimeError(
                f"custom op {self._name!r} is a host callback with no "
                "backward; call it on a detached tensor (x.detach()) or "
                "under paddle.no_grad()")

        def fn(v):
            return jax.pure_callback(
                self._host, jax.ShapeDtypeStruct(v.shape, jnp.float32), v,
                vmap_method="sequential")
        return call_op(self._name, fn, (x,))


class CppExtension:
    def __init__(self, lib_path, functions):
        self._lib = ctypes.CDLL(lib_path)
        self.lib_path = lib_path
        for fname in functions:
            setattr(self, fname, _HostOp(getattr(self._lib, fname), fname))


def load(name, sources, functions=None, extra_cflags=None, verbose=False):
    """Compile `sources` and return a CppExtension exposing `functions`.

    functions defaults to [name]. Each must follow the extern-C elementwise
    contract in the module docstring.
    """
    lib_path = _compile(name, sources, extra_cflags)
    return CppExtension(lib_path, functions or [name])


def CUDAExtension(sources, *args, **kwargs):
    """Reference cpp_extension.py CUDAExtension — no CUDA toolchain on a
    TPU host; C++ extensions go through CppExtension/setup."""
    raise RuntimeError(
        "CUDAExtension needs nvcc; this is a TPU host — use "
        "CppExtension(sources) for C++ ops (XLA/Pallas own device code)")


def get_build_directory(verbose=False):
    """Reference cpp_extension/extension_utils.py get_build_directory
    (PADDLE_EXTENSION_DIR override honored)."""
    import os
    root = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    os.makedirs(root, exist_ok=True)
    return root


def setup(**attr):
    """Reference cpp_extension.py:78 setup — build the ext_modules with the
    host C++ toolchain via setuptools; on this image the JIT `load` path
    (ctypes) is the supported route, so setup() compiles each extension's
    sources through the same pipeline and records the artifacts."""
    name = attr.get("name", "paddle_tpu_ext")
    exts = attr.get("ext_modules") or []
    if not isinstance(exts, (list, tuple)):
        exts = [exts]
    built = []
    for ext in exts:
        sources = getattr(ext, "sources", None) or (
            ext.get("sources") if isinstance(ext, dict) else None)
        if not sources:
            continue
        mod = load(name=getattr(ext, "name", name), sources=sources,
                   extra_cflags=attr.get("extra_compile_args"))
        built.append(mod)
    return built


__all__ += ["CUDAExtension", "setup", "get_build_directory"]
