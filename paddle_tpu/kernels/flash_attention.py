"""Flash attention (Pallas, TPU).

Reference analog: fluid/operators/fused/fused_attention_op.cu + fmha_ref.h —
the reference's fused MHA. TPU-native design: blockwise online-softmax
attention in VMEM (Rabe&Staats / FlashAttention recipe), one grid cell per
(batch*head, q_block); K/V stream through VMEM blocks so the N×N score matrix
never hits HBM.

Forward runs as a Pallas kernel. Backward currently recomputes attention
blockwise via XLA (same FLOPs as flash-bwd, XLA fuses it well); a full Pallas
backward is a planned upgrade.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from ._common import ZERO as _SHARED_ZERO, on_tpu as _on_tpu

__all__ = ["flash_attention_bnhd", "is_eligible"]

_NEG_INF = -1e30


# below this sequence length XLA's fused attention wins (measured on v5e:
# GPT-2 seq-1024 trains 1.5x faster through the XLA path); above it the N^2
# score materialization starts to dominate HBM and the streaming kernel pays
# off
FLASH_MIN_SEQ = 2048


def is_eligible(q, k, v, mask, dropout_p, is_causal=False):
    """Flash path requires: TPU, no explicit mask (causal flag ok), no dropout,
    block-friendly seq lengths and head_dim, and long-enough sequences that
    blockwise streaming beats XLA's fused N^2 attention."""
    if not _HAS_PALLAS or not _on_tpu():
        return False
    if mask is not None or dropout_p:
        return False
    if q.ndim != 4:
        return False
    b, n, h, d = q.shape
    m = k.shape[1]
    if d not in (64, 128, 256):
        return False
    if is_causal and n != m:
        # kv-cache decode/prefill shapes (m > n) use bottom-right causal
        # alignment; this kernel's causal masking is top-left (n == m) only.
        # Non-causal cross-attention has no mask, so any n/m is fine.
        return False
    if n % 128 != 0 or m % 128 != 0:
        return False
    from ..framework.flags import FLAGS
    if not FLAGS.use_flash_attention:
        return False
    if max(n, m) < FLASH_MIN_SEQ:
        return False
    return True


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, scale,
                block_q, block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [block_q, d]

    def body(start_k, carry):
        o_acc, m_acc, l_acc = carry
        k_blk = k_ref[0, pl.ds(start_k * block_k, block_k), :] \
            .astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(start_k * block_k, block_k), :] \
            .astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(_NEG_INF))
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_acc - m_new)
        l_new = alpha * l_acc + jnp.sum(p, axis=1)
        o_new = o_acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    num_k_blocks = seq_k // block_k
    if causal:
        # only iterate K blocks up to (and including) the diagonal;
        # block_q % block_k == 0 keeps this pure integer-multiply on the
        # traced program id (no traced floor-div)
        assert block_q % block_k == 0
        last = (qi + 1) * (block_q // block_k)
        upper = jnp.minimum(last, num_k_blocks)
    else:
        upper = num_k_blocks

    d = q.shape[-1]
    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    # i32 loop bounds: x64 mode would otherwise make an i64 counter, which
    # Mosaic cannot legalize
    o_acc, m_acc, l_acc = jax.lax.fori_loop(
        jnp.int32(0), jnp.asarray(upper, jnp.int32), body, (o0, m0, l0))
    l_safe = jnp.maximum(l_acc, jnp.float32(1e-30))
    o_ref[0] = (o_acc / l_safe[:, None]).astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, scale, block_q=128, block_k=128):
    """q,k,v: [B, N, H, D] — runs the kernel per (b*h, q_block)."""
    b, n, h, d = q.shape
    m = k.shape[1]
    # fold batch & heads, move seq to the row dim: [B*H, N, D]
    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, n, d)
    kf = jnp.swapaxes(k, 1, 2).reshape(b * h, m, d)
    vf = jnp.swapaxes(v, 1, 2).reshape(b * h, m, d)

    grid = (b * h, n // block_q)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k, seq_k=m)
    # index maps must emit i32 (see kernels/_common.py)
    zero = _SHARED_ZERO
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, zero)),
            pl.BlockSpec((1, m, d), lambda bh, qi: (bh, zero, zero)),
            pl.BlockSpec((1, m, d), lambda bh, qi: (bh, zero, zero)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi: (bh, qi, zero)),
        out_shape=jax.ShapeDtypeStruct((b * h, n, d), q.dtype),
    )(qf, kf, vf)
    return out.reshape(b, h, n, d).swapaxes(1, 2)  # back to [B, N, H, D]


def _plain_attention_vjp(q, k, v, causal, scale):
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kt = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vt = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhnd,bhmd->bhnm", qt, kt) * scale
    if causal:
        n, m = s.shape[-2], s.shape[-1]
        # bottom-right alignment, matching _plain_attention (only n == m
        # reaches the flash path today, where the two coincide)
        q_pos = jnp.arange(n)[:, None] + (m - n)
        mask = q_pos >= jnp.arange(m)[None, :]
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhnm,bhmd->bhnd", p, vt)
    return jnp.swapaxes(o, 1, 2).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_bnhd(q, k, v, causal=False, scale=None):
    """Flash attention over [batch, seq, heads, head_dim] tensors."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, causal, scale)


def _fa_fwd(q, k, v, causal, scale):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out = _flash_fwd(q, k, v, causal, scale)
    return out, (q, k, v)


def _fa_bwd(causal, scale, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # recompute-based backward: XLA differentiates the reference formulation;
    # FLOP-equivalent to flash-bwd, peak memory bounded by one fused cluster
    _, vjp = jax.vjp(lambda qq, kk, vv:
                     _plain_attention_vjp(qq, kk, vv, causal, scale), q, k, v)
    return vjp(g)


flash_attention_bnhd.defvjp(_fa_fwd, _fa_bwd)
