"""Flash attention (Pallas, TPU).

Reference analog: fluid/operators/fused/fused_attention_op.cu + fmha_ref.h —
the reference's fused MHA. TPU-native design: blockwise online-softmax
attention in VMEM (Rabe&Staats / FlashAttention recipe), one grid cell per
(batch*head, q_block); K/V stream through VMEM blocks so the N×N score matrix
never hits HBM.

Forward and backward both run as Pallas kernels (FlashAttention-2
decomposition: forward saves the per-row logsumexp; backward is two kernels —
dQ gridded over q blocks, dK/dV gridded over k blocks — so no atomics and no
N x N materialization anywhere). Measured v5e, GPT-2 bench shape (b16 h12
n1024 d64): fwd 0.93ms vs XLA 2.03ms; fwd+bwd 3.7ms vs XLA 5.7ms.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from ._common import ZERO as _SHARED_ZERO, on_tpu as _on_tpu

__all__ = ["flash_attention_bnhd", "is_eligible"]

_NEG_INF = -1e30


# with the Pallas backward and 512-wide blocks the flash path beats XLA's
# fused attention from seq 1024 up (v5e, GPT-2 shape: 3.7ms vs 5.7ms
# fwd+bwd); below that the kernel launch overhead loses to XLA's N^2 path
FLASH_MIN_SEQ = 1024


def _auto_blocks(n, m):
    """512-wide tiles win on v5e (VMEM-resident [512,512] f32 score tile
    saturates the MXU; 128-wide tiles leave it 3x underutilized). The block
    must DIVIDE the sequence length — the pallas grids floor-divide, so a
    non-dividing block would silently drop the tail rows/keys."""
    def largest_dividing(seq):
        for cand in (512, 256, 128):
            if seq % cand == 0:
                return cand
        return min(seq, 128)
    bq = largest_dividing(n)
    bk = largest_dividing(m)
    # causal diagonal trimming requires block_q % block_k == 0
    if bq % bk:
        bk = math.gcd(bq, bk)
    return bq, bk


def is_eligible(q, k, v, mask, dropout_p, is_causal=False):
    """Flash path requires: TPU, no explicit mask (causal flag ok), no dropout,
    block-friendly seq lengths and head_dim, and long-enough sequences that
    blockwise streaming beats XLA's fused N^2 attention."""
    if not _HAS_PALLAS or not _on_tpu():
        return False
    if mask is not None or dropout_p:
        return False
    if q.ndim != 4:
        return False
    b, n, h, d = q.shape
    m = k.shape[1]
    if d not in (64, 128, 256):
        return False
    if is_causal and n != m:
        # kv-cache decode/prefill shapes (m > n) use bottom-right causal
        # alignment; this kernel's causal masking is top-left (n == m) only.
        # Non-causal cross-attention has no mask, so any n/m is fine.
        return False
    if n % 128 != 0 or m % 128 != 0:
        return False
    from ..framework.flags import FLAGS
    if not FLAGS.use_flash_attention:
        return False
    if max(n, m) < FLASH_MIN_SEQ:
        return False
    return True


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                block_q, block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [block_q, d]

    def body(start_k, carry):
        o_acc, m_acc, l_acc = carry
        k_blk = k_ref[0, pl.ds(start_k * block_k, block_k), :] \
            .astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(start_k * block_k, block_k), :] \
            .astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = start_k * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(_NEG_INF))
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_acc - m_new)
        l_new = alpha * l_acc + jnp.sum(p, axis=1)
        o_new = o_acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    num_k_blocks = seq_k // block_k
    if causal:
        # only iterate K blocks up to (and including) the diagonal;
        # block_q % block_k == 0 keeps this pure integer-multiply on the
        # traced program id (no traced floor-div)
        assert block_q % block_k == 0
        last = (qi + 1) * (block_q // block_k)
        upper = jnp.minimum(last, num_k_blocks)
    else:
        upper = num_k_blocks

    d = q.shape[-1]
    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    # i32 loop bounds: x64 mode would otherwise make an i64 counter, which
    # Mosaic cannot legalize
    o_acc, m_acc, l_acc = jax.lax.fori_loop(
        jnp.int32(0), jnp.asarray(upper, jnp.int32), body, (o0, m0, l0))
    l_safe = jnp.maximum(l_acc, jnp.float32(1e-30))
    o_ref[0] = (o_acc / l_safe[:, None]).astype(o_ref.dtype)
    # logsumexp per row, needed by the Pallas backward ([bq, 1] tile: TPU
    # blocks must be >= 2-D)
    lse_ref[0] = (m_acc + jnp.log(l_safe))[:, None]


def _flash_fwd(q, k, v, causal, scale, block_q=None, block_k=None,
               interpret=False):
    """q,k,v: [B, N, H, D] — runs the kernel per (b*h, q_block).

    Returns (out [B,N,H,D], lse [B*H, N] float32)."""
    b, n, h, d = q.shape
    m = k.shape[1]
    if block_q is None or block_k is None:
        block_q, block_k = _auto_blocks(n, m)
    # fold batch & heads, move seq to the row dim: [B*H, N, D]
    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, n, d)
    kf = jnp.swapaxes(k, 1, 2).reshape(b * h, m, d)
    vf = jnp.swapaxes(v, 1, 2).reshape(b * h, m, d)

    grid = (b * h, n // block_q)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_q=block_q, block_k=block_k, seq_k=m)
    # index maps must emit i32 (see kernels/_common.py)
    zero = _SHARED_ZERO
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, zero)),
            pl.BlockSpec((1, m, d), lambda bh, qi: (bh, zero, zero)),
            pl.BlockSpec((1, m, d), lambda bh, qi: (bh, zero, zero)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, zero)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, zero)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, n, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, n, d).swapaxes(1, 2), lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               causal, scale, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                   # [bq, d]
    do = do_ref[0].astype(jnp.float32)                 # [bq, d]
    lse = lse_ref[0]                                   # [bq, 1]
    delta = delta_ref[0]                               # [bq, 1]

    def body(ki, dq_acc):
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse)                           # normalized probs
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq_acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    num_k_blocks = seq_k // block_k
    if causal:
        assert block_q % block_k == 0
        upper = jnp.minimum((qi + 1) * (block_q // block_k), num_k_blocks)
    else:
        upper = num_k_blocks
    dq0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    dq = jax.lax.fori_loop(jnp.int32(0), jnp.asarray(upper, jnp.int32),
                           body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, causal, scale, block_q, block_k, seq_q):
    ki = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)               # [bk, d]
    v_blk = v_ref[0].astype(jnp.float32)               # [bk, d]

    num_q_blocks = seq_q // block_q
    if causal:
        # only q blocks at/after this k block's diagonal contribute; loop a
        # traced COUNT from a static 0 with a shifted induction variable.
        # lax.div, not //: Mosaic's floor_divide lowering recurses through
        # convert_element_type under x64
        assert block_q % block_k == 0
        first = jax.lax.div(ki * jnp.int32(block_k), jnp.int32(block_q))
    else:
        first = 0

    def body(j, carry):
        qi = j + first
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), :]    # [bq, 1]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, jnp.float32(_NEG_INF))
        p = jnp.exp(s - lse)                           # [bq, bk]
        dv_new = dv_acc + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # p^T @ do
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # ds^T @ q
        return dk_new, dv_new

    d = k_blk.shape[-1]
    init = (jnp.zeros((block_k, d), jnp.float32),
            jnp.zeros((block_k, d), jnp.float32))
    count = jnp.asarray(num_q_blocks - first, jnp.int32)
    dk, dv = jax.lax.fori_loop(jnp.int32(0), count, body, init)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, scale,
               block_q=None, block_k=None, interpret=False):
    """Pallas flash backward: dQ via one kernel over q blocks, dK/dV via one
    kernel over k blocks — FlashAttention-2 decomposition, no atomics, no
    N x N materialization."""
    b, n, h, d = q.shape
    m = k.shape[1]
    if block_q is None or block_k is None:
        block_q, block_k = _auto_blocks(n, m)
    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, n, d)
    kf = jnp.swapaxes(k, 1, 2).reshape(b * h, m, d)
    vf = jnp.swapaxes(v, 1, 2).reshape(b * h, m, d)
    of = jnp.swapaxes(out, 1, 2).reshape(b * h, n, d)
    gf = jnp.swapaxes(g, 1, 2).reshape(b * h, n, d)
    # rescale q once here so fwd/bwd agree on s = (q*scale) @ k^T
    delta = jnp.sum(of.astype(jnp.float32) * gf.astype(jnp.float32),
                    axis=-1, keepdims=True)             # [bh, n, 1]
    zero = _SHARED_ZERO

    dq_kernel = functools.partial(_dq_kernel, causal=causal, scale=scale,
                                  block_q=block_q, block_k=block_k, seq_k=m)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, n // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, zero)),
            pl.BlockSpec((1, m, d), lambda bh, qi: (bh, zero, zero)),
            pl.BlockSpec((1, m, d), lambda bh, qi: (bh, zero, zero)),
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, zero)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, zero)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, zero)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qi: (bh, qi, zero)),
        out_shape=jax.ShapeDtypeStruct((b * h, n, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    dkv_kernel = functools.partial(_dkv_kernel, causal=causal, scale=scale,
                                   block_q=block_q, block_k=block_k, seq_q=n)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, m // block_k),
        in_specs=[
            pl.BlockSpec((1, n, d), lambda bh, ki: (bh, zero, zero)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, zero)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, zero)),
            pl.BlockSpec((1, n, d), lambda bh, ki: (bh, zero, zero)),
            pl.BlockSpec((1, n, 1), lambda bh, ki: (bh, zero, zero)),
            pl.BlockSpec((1, n, 1), lambda bh, ki: (bh, zero, zero)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, zero)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, zero)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, m, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, m, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    def unfold(t, nn):
        return t.reshape(b, h, nn, d).swapaxes(1, 2)

    return unfold(dq, n), unfold(dk, m), unfold(dv, m)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_bnhd(q, k, v, causal=False, scale=None):
    """Flash attention over [batch, seq, heads, head_dim] tensors."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, causal, scale)[0]


def _fa_fwd(q, k, v, causal, scale):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, lse = _flash_fwd(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_bwd(q, k, v, out, lse, g, causal, scale)


flash_attention_bnhd.defvjp(_fa_fwd, _fa_bwd)
