"""Blockwise paged decode attention over the block-pool KV cache.

The serving decode step used to gather every slot's paged KV history into
a dense ``[S, T, H, D]`` context per layer (nn/functional/attention.py)
— the main obstacle between the 0.178 ms/step CPU proxy and the 0.08 ms
TPU target. This module is the FlashAttention-style fix specialized to
PagedAttention's memory model: stream the pool's KV blocks through the
block table with ONLINE (streaming) softmax, fp32 accumulators, one block
resident at a time — the dense context never exists.

Two implementations with identical semantics:

  * `pallas_paged_attention` — the TPU kernel. Grid ``(S*H, M)``; the
    block table and (effective) lengths ride as scalar-prefetch
    arguments, so each grid cell's BlockSpec index map picks its pool
    block ``tables[s, j]`` directly — the DMA engine walks the page
    table, the kernel body only ever sees one ``[bs, D]`` tile in VMEM.
    int8 pools dequantize inside the load (`q * scale / 127`), so the
    fp values exist only in VMEM. Length masking keeps the null-block
    branch-free contract: padded/inactive table entries read block 0 and
    their scores are masked, never branched on. Runs under
    ``interpret=True`` on CPU for the fused-vs-reference parity tests.
  * `blockwise_paged_attention` — pure-JAX `lax.scan` over block chunks
    with the same online-softmax recurrence. This is the CPU/parity
    fallback AND a standalone win: it replaces the dense gather's
    ``[S, T, H, D]`` materialization with cache-resident chunks, so it
    beats the gather on the serve CPU legs from seq ~1k up
    (tools/perf_smoke.py leg j guards the floor).

Numerics: scores, the softmax recurrence, and the output accumulator are
fp32 regardless of the query/pool dtype; only the final output casts back
to the query dtype. Masked positions contribute exactly zero probability
(explicit `where`, not just a large negative score).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from .._common import ZERO as _ZERO, on_tpu as _on_tpu
from ...quantization.kv_cache import QMAX as _QMAX, dequantize as _dequant

__all__ = ["blockwise_paged_attention", "pallas_paged_attention",
           "is_eligible"]

_NEG_INF = -1e30

# blockwise scan chunking: gather KV per scan step in chunks targeting
# this many BYTES per pool side (multiple pool blocks per step when
# block_size is small) — big enough to amortize the scan-iteration
# overhead, small enough to stay cache-resident instead of
# re-materializing the dense context. Tokens are capped so tiny-head
# shapes don't degenerate into one dense chunk
_CHUNK_TARGET_BYTES = 256 * 1024
_CHUNK_TOKENS_MAX = 512


def is_eligible(head_dim, block_size):
    """Can the Pallas kernel run compiled (non-interpret) here?
    Returns (ok, why) — `why` is the attribution detail for the
    `kernel.fallback` flight-recorder event when not."""
    if not _HAS_PALLAS:
        return False, "no_pallas"
    if not _on_tpu():
        return False, "not_on_tpu"
    if head_dim is None or head_dim % 64 != 0:
        # the [bs, D] tiles want lane-aligned head dims; odd heads take
        # the blockwise path (same math, no Mosaic constraints)
        return False, "head_dim_unaligned"
    if block_size is None or block_size % 8 != 0:
        return False, "block_size_unaligned"
    return True, None


# ---------------------------------------------------------------------------
# pure-JAX blockwise reference path (lax.scan over block chunks)
# ---------------------------------------------------------------------------

def blockwise_paged_attention(q, k_pool, v_pool, block_tables, lens,
                              block_size, k_scales=None, v_scales=None,
                              chunk_blocks=None):
    """Online-softmax paged attention, one KV chunk at a time.

    q: ``[S, H, D]`` this step's queries; k_pool/v_pool:
    ``[num_blocks, bs, H, D]`` (fp, or int8 with `k_scales`/`v_scales`
    ``[num_blocks, H]``); block_tables: ``[S, M]`` int32; lens: ``[S]``
    int32 EFFECTIVE lengths (position p attends iff p <= lens[s];
    inactive slots pass 0). Returns ``[S, H, D]`` in q's dtype.
    """
    s, h, d = q.shape
    m = block_tables.shape[1]
    bs = int(block_size)
    quant = k_scales is not None
    if chunk_blocks is None:
        per_token = h * d * jnp.dtype(jnp.float32).itemsize
        tokens = min(max(_CHUNK_TARGET_BYTES // per_token, bs),
                     _CHUNK_TOKENS_MAX)
        chunk_blocks = max(1, int(tokens) // bs)
    chunk_blocks = min(int(chunk_blocks), m)
    n_chunks = -(-m // chunk_blocks)
    pad = n_chunks * chunk_blocks - m
    tables = block_tables
    if pad:
        # padded entries read the null block; their positions exceed
        # every possible length, so the mask kills them
        tables = jnp.pad(tables, ((0, 0), (0, pad)))
    # [n_chunks, S, C]: scan consumes chunks along the leading axis
    tabs = jnp.swapaxes(
        tables.reshape(s, n_chunks, chunk_blocks), 0, 1)
    q32 = q.astype(jnp.float32) * (1.0 / math.sqrt(d))
    t_chunk = chunk_blocks * bs
    offs = jnp.arange(t_chunk, dtype=jnp.int32)

    def step(carry, xs):
        acc, mx, l = carry
        ci, bids = xs                                   # [], [S, C]
        kc = k_pool[bids]                               # [S, C, bs, H, D]
        vc = v_pool[bids]
        if quant:
            kc = _dequant(kc, k_scales[bids])
            vc = _dequant(vc, v_scales[bids])
        else:
            kc = kc.astype(jnp.float32)
            vc = vc.astype(jnp.float32)
        kc = kc.reshape(s, t_chunk, h, d)
        vc = vc.reshape(s, t_chunk, h, d)
        scores = jnp.einsum("shd,sthd->sht", q32, kc)
        pos = ci * t_chunk + offs
        valid = pos[None, :] <= lens[:, None]           # [S, t]
        scores = jnp.where(valid[:, None, :], scores,
                           jnp.float32(_NEG_INF))
        m_new = jnp.maximum(mx, jnp.max(scores, axis=-1))
        # explicit zero for masked slots: a fully-masked chunk must not
        # leak exp(NEG - NEG) == 1 into the row sums
        p = jnp.where(valid[:, None, :],
                      jnp.exp(scores - m_new[..., None]), 0.0)
        alpha = jnp.exp(mx - m_new)
        l = alpha * l + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] \
            + jnp.einsum("sht,sthd->shd", p, vc)
        return (acc, m_new, l), None

    acc0 = jnp.zeros((s, h, d), jnp.float32)
    m0 = jnp.full((s, h), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((s, h), jnp.float32)
    (acc, _, l), _ = jax.lax.scan(
        step, (acc0, m0, l0),
        (jnp.arange(n_chunks, dtype=jnp.int32), tabs))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel: one grid cell per (slot*head, table entry)
# ---------------------------------------------------------------------------

def _decode_kernel(tab_ref, lens_ref, q_ref, k_ref, v_ref, *rest,
                   block_size, heads, quantized):
    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    sh = pl.program_id(0)
    j = pl.program_id(1)
    s = jax.lax.div(sh, jnp.int32(heads))

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    qv = q_ref[...].astype(jnp.float32)                # [1, D] (pre-scaled)
    k = k_ref[:, 0, :].astype(jnp.float32)             # [bs, D]
    v = v_ref[:, 0, :].astype(jnp.float32)
    if quantized:
        # dequant fused into the block load: fp K/V exist only in VMEM
        k = k * (ks_ref[0, 0] * (1.0 / _QMAX))
        v = v * (vs_ref[0, 0] * (1.0 / _QMAX))
    scores = jax.lax.dot_general(
        k, qv, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [bs, 1]
    pos = j * jnp.int32(block_size) + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 0)
    valid = pos <= lens_ref[s]
    scores = jnp.where(valid, scores, jnp.float32(_NEG_INF))
    m_prev = m_ref[0, 0]
    m_new = jnp.maximum(m_prev, jnp.max(scores))
    p = jnp.where(valid, jnp.exp(scores - m_new), 0.0)  # [bs, 1]
    alpha = jnp.exp(m_prev - m_new)
    l_ref[0, 0] = alpha * l_ref[0, 0] + jnp.sum(p)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p, v, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # [1, D]
    m_ref[0, 0] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _flush():
        o_ref[...] = (acc_ref[...]
                      / jnp.maximum(l_ref[0, 0], 1e-30)).astype(o_ref.dtype)


def pallas_paged_attention(q, k_pool, v_pool, block_tables, lens,
                           block_size, k_scales=None, v_scales=None,
                           interpret=False):
    """The Pallas kernel: same contract as `blockwise_paged_attention`.
    `interpret=True` runs the kernel through the Pallas interpreter on
    any backend (the CPU parity path)."""
    s, h, d = q.shape
    bs = int(block_size)
    m = block_tables.shape[1]
    quant = k_scales is not None
    zero = _ZERO
    qf = (q.astype(jnp.float32) * (1.0 / math.sqrt(d))).reshape(s * h, d)
    tables = block_tables.astype(jnp.int32)
    lens32 = lens.astype(jnp.int32)

    # index maps receive (grid ids..., scalar-prefetch refs): the block
    # table IS the page table the DMA walks
    in_specs = [
        pl.BlockSpec((1, d), lambda sh, j, t, l: (sh, zero)),
        pl.BlockSpec((None, bs, 1, d),
                     lambda sh, j, t, l: (t[sh // h, j], zero, sh % h,
                                          zero)),
        pl.BlockSpec((None, bs, 1, d),
                     lambda sh, j, t, l: (t[sh // h, j], zero, sh % h,
                                          zero)),
    ]
    args = [tables, lens32, qf, k_pool, v_pool]
    if quant:
        spec = pl.BlockSpec((None, 1, 1),
                            lambda sh, j, t, l: (t[sh // h, j], sh % h,
                                                 zero))
        in_specs += [spec, spec]
        args += [k_scales[..., None], v_scales[..., None]]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s * h, m),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, d), lambda sh, j, t, l: (sh, zero)),
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32),
                        pltpu.VMEM((1, 1), jnp.float32),
                        pltpu.VMEM((1, 1), jnp.float32)])
    kernel = functools.partial(_decode_kernel, block_size=bs, heads=h,
                               quantized=quant)
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s * h, d), q.dtype),
        interpret=interpret)(*args)
    return out.reshape(s, h, d)
