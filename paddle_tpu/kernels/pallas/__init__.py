"""Pallas kernel tier for the serving hot path.

Reference analog: the PHI fused-kernel layer (fluid/operators/fused/) —
here the fusions target the continuous-batching decode step instead of
training graphs: blockwise paged decode attention that consumes the
block-pool KV cache (serving/cache.py) directly, with int8 dequant fused
into the block loads (quantization/kv_cache.py).

Modules import lazily from the routing layer
(nn/functional/attention.py) so a CPU-only process never pays the Pallas
import unless a kernel is actually requested.
"""
from . import paged_attention  # noqa: F401
