"""Pallas TPU kernels — the hot fused ops.

Reference analog: paddle/fluid/operators/fused/ (fused_attention_op.cu,
fused_feedforward_op.cu, fused_softmax_mask). Here each is a Pallas kernel
targeting MXU/VMEM directly.
"""
from . import flash_attention  # noqa: F401
from . import cross_entropy  # noqa: F401
from . import fused_ln  # noqa: F401
