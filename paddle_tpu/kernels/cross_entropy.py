"""Fused softmax cross-entropy (Pallas, TPU).

Reference analog: fluid/operators/collective/c_softmax_with_cross_entropy_op
+ phi softmax_with_cross_entropy kernels — the reference fuses softmax+CE on
GPU to avoid materializing log-probs over a 50k vocab.

TPU-native design: vocab-blocked online logsumexp. The grid is
(row_blocks, vocab_blocks); vocab blocks run sequentially per row block with
(running-max, running-sum, picked-logit) carried in VMEM scratch, so the
forward never writes a [rows, vocab] log-softmax to HBM. The backward is a
second blocked kernel writing grad = (softmax - onehot) * g per block. For
GPT-2 (V=50304) this removes a [B*S, V] f32 round-trip per step.

Per-row 1-D arrays (labels/loss/lse/g) are carried as [row_blocks, 128] so
their minor dim matches the TPU lane tiling (Mosaic rejects XLA's 1-D s32
T(1024) layout).

Off-TPU the same kernels run under the Pallas interpreter in tests; the
public entry point falls back to XLA when ineligible.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from ._common import ZERO as _ZERO, on_tpu as _on_tpu

__all__ = ["fused_softmax_cross_entropy", "is_eligible", "masked_reduce"]


def masked_reduce(nll, lab_v, ignore_index, reduction):
    """Shared ignore_index masking + reduction used by every fused-CE entry
    point (nn.functional.cross_entropy, incubate fused_softmax_cross_entropy)
    so their semantics cannot drift apart."""
    valid = lab_v != ignore_index
    nll = jnp.where(valid, nll, 0.0)
    if reduction == "mean":
        denom = jnp.sum(valid.astype(jnp.float32))
        return jnp.sum(nll) / jnp.maximum(denom, 1.0)
    if reduction == "sum":
        return jnp.sum(nll)
    return nll

_NEG_INF = -1e30
_BLOCK_R = 128
_BLOCK_V = 2048


def is_eligible(logits, labels, force=False):
    """force=True skips the FLAGS gate (explicit incubate entry point) but
    still requires a TPU + supported shapes."""
    if not _HAS_PALLAS or not _on_tpu():
        return False
    if logits.ndim != 2 or labels.ndim != 1:
        return False
    if not force:
        from ..framework.flags import FLAGS
        if not getattr(FLAGS, "use_fused_cross_entropy", True):
            return False
        # below this the XLA-fused CE is fine; above, the blocked kernel
        # saves HBM
        if logits.shape[1] < 8192:
            return False
    return True


def _fwd_kernel(lab_ref, logits_ref, loss_ref, lse_ref, m_ref, l_ref, p_ref,
                *, block_v, n_vblocks):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        p_ref[...] = jnp.zeros_like(p_ref)

    lab = lab_ref[0, 0].astype(jnp.int32)                      # [block_r]
    blk = logits_ref[...].astype(jnp.float32)               # [block_r, block_v]
    col = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, blk.shape, 1)

    m_acc, l_acc = m_ref[0], l_ref[0]
    m_new = jnp.maximum(m_acc, jnp.max(blk, axis=1))
    alpha = jnp.exp(m_acc - m_new)
    l_new = alpha * l_acc + jnp.sum(jnp.exp(blk - m_new[:, None]), axis=1)
    hit = col == lab[:, None]
    m_ref[0] = m_new
    l_ref[0] = l_new
    p_ref[0] = p_ref[0] + jnp.sum(jnp.where(hit, blk, 0.0), axis=1)

    @pl.when(vi == n_vblocks - 1)
    def _finish():
        lse = jnp.log(l_ref[0]) + m_ref[0]
        lse_ref[0, 0] = lse
        loss_ref[0, 0] = lse - p_ref[0]


def _bwd_kernel(lab_ref, g_ref, lse_ref, logits_ref, dlogits_ref, *, block_v):
    vi = pl.program_id(1)
    lab = lab_ref[0, 0].astype(jnp.int32)                      # [block_r]
    g = g_ref[0, 0].astype(jnp.float32)                        # [block_r]
    lse = lse_ref[0, 0]                                     # [block_r]
    blk = logits_ref[...].astype(jnp.float32)               # [block_r, block_v]
    col = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, blk.shape, 1)
    p = jnp.exp(blk - lse[:, None])
    onehot = (col == lab[:, None]).astype(jnp.float32)
    dlogits_ref[...] = ((p - onehot) * g[:, None]).astype(dlogits_ref.dtype)


def _pad_inputs(logits, labels, extra_rows=()):
    """Pad rows to _BLOCK_R and vocab to _BLOCK_V multiples, then fold the
    row vectors to [row_blocks, _BLOCK_R]. Vocab is padded with -inf so the
    padded columns vanish under softmax."""
    r, v = logits.shape
    pad_r = (-r) % _BLOCK_R
    pad_v = (-v) % _BLOCK_V
    if pad_r or pad_v:
        logits = jnp.pad(logits, ((0, pad_r), (0, pad_v)),
                         constant_values=_NEG_INF)
    labels = jnp.pad(labels, (0, pad_r), constant_values=-1) if pad_r \
        else labels
    rb = (r + pad_r) // _BLOCK_R
    # row vectors carried as [rb, 1, 128]: block (1, 1, 128) keeps the last
    # two dims aligned with the (sublane=dim, lane=128) tiling Mosaic needs
    extras = [(jnp.pad(e, (0, pad_r)) if pad_r else e).reshape(rb, 1, _BLOCK_R)
              for e in extra_rows]
    return logits, labels.reshape(rb, 1, _BLOCK_R), extras


def _row_spec():
    return pl.BlockSpec((1, 1, _BLOCK_R), lambda ri, vi: (ri, _ZERO, _ZERO))


def _fwd(logits, labels, interpret):
    r, v = logits.shape
    logits_p, labels_p, _ = _pad_inputs(logits, labels)
    rp, vp = logits_p.shape
    rb = rp // _BLOCK_R
    kernel = functools.partial(_fwd_kernel, block_v=_BLOCK_V,
                               n_vblocks=vp // _BLOCK_V)
    loss, lse = pl.pallas_call(
        kernel,
        grid=(rb, vp // _BLOCK_V),
        in_specs=[
            _row_spec(),
            pl.BlockSpec((_BLOCK_R, _BLOCK_V), lambda ri, vi: (ri, vi)),
        ],
        out_specs=[_row_spec(), _row_spec()],
        out_shape=[
            jax.ShapeDtypeStruct((rb, 1, _BLOCK_R), jnp.float32),
            jax.ShapeDtypeStruct((rb, 1, _BLOCK_R), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, _BLOCK_R), jnp.float32),
            pltpu.VMEM((1, _BLOCK_R), jnp.float32),
            pltpu.VMEM((1, _BLOCK_R), jnp.float32),
        ],
        interpret=interpret,
    )(labels_p, logits_p)
    return loss.reshape(-1)[:r], lse.reshape(-1)[:r]


def _bwd(logits, labels, lse, g, interpret):
    r, v = logits.shape
    logits_p, labels_p, (g_p, lse_p) = _pad_inputs(logits, labels, (g, lse))
    rp, vp = logits_p.shape
    kernel = functools.partial(_bwd_kernel, block_v=_BLOCK_V)
    dlogits = pl.pallas_call(
        kernel,
        grid=(rp // _BLOCK_R, vp // _BLOCK_V),
        in_specs=[
            _row_spec(), _row_spec(), _row_spec(),
            pl.BlockSpec((_BLOCK_R, _BLOCK_V), lambda ri, vi: (ri, vi)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_R, _BLOCK_V), lambda ri, vi: (ri, vi)),
        out_shape=jax.ShapeDtypeStruct((rp, vp), logits.dtype),
        interpret=interpret,
    )(labels_p, g_p, lse_p, logits_p)
    return dlogits[:r, :v]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_softmax_cross_entropy(logits, labels, interpret=False):
    """Per-row CE loss [R] for logits [R, V], int labels [R].

    Rows with a negative label (ignore_index) produce loss = lse (no picked
    logit); mask them in the caller, as the XLA path does.
    """
    loss, _ = _fwd(logits, labels, interpret)
    return loss


def _vjp_fwd(logits, labels, interpret):
    loss, lse = _fwd(logits, labels, interpret)
    return loss, (logits, labels, lse)


def _vjp_bwd(interpret, res, g):
    logits, labels, lse = res
    return _bwd(logits, labels, lse, g, interpret), None


fused_softmax_cross_entropy.defvjp(_vjp_fwd, _vjp_bwd)
