"""Shared helpers for the Pallas TPU kernels."""
from __future__ import annotations

import numpy as np
import jax

# index maps must emit i32 — a python literal 0 traces as i64 under the
# framework's x64 mode, which Mosaic cannot legalize
ZERO = np.int32(0)

# platforms that execute Pallas TPU kernels (axon = tunneled v5e chip)
TPU_PLATFORMS = ("tpu", "axon")


def on_tpu():
    try:
        return jax.devices()[0].platform in TPU_PLATFORMS
    except Exception:
        return False
