"""Fused bias + dropout + residual + LayerNorm (Pallas, TPU).

Reference analog: fluid/operators/fused/fused_bias_dropout_residual_layer_norm
_op.cu (+ fused_dropout_helper.h) — the reference's epilogue fusion after
attention/FFN projections.

TPU-native design: one row-blocked kernel computes
    y = LayerNorm((x + bias) + residual) * scale + shift
entirely in VMEM — a single HBM read of x/residual and a single write of y,
instead of separate add/reduce/normalize round-trips. Rows are the sublane
dim; the full hidden dim stays resident per row block.

Dropout (training) falls back to the XLA path: TPU dropout is cheap under
XLA fusion and keeping RNG out of the kernel keeps it deterministic per
(seed, position) under pjit. The backward recomputes via XLA (elementwise +
row reductions fuse into two kernels).

Off-TPU the kernel runs under the Pallas interpreter in tests; the public
entry point falls back to XLA when ineligible.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from ._common import ZERO as _ZERO, on_tpu as _on_tpu

__all__ = ["fused_bias_residual_layer_norm", "is_eligible"]


def is_eligible(x, d):
    if not _HAS_PALLAS or not _on_tpu():
        return False
    from ..framework.flags import FLAGS
    if not getattr(FLAGS, "use_fused_layer_norm", True):
        return False
    # d must tile the lane dim and leave VMEM room for at least an 8-row block
    return d % 128 == 0 and _pick_block_r(d) is not None


def _pick_block_r(d):
    # keep x/residual/out blocks around ~6MB of VMEM; None = too large, the
    # caller must fall back to XLA
    budget = 6 * 1024 * 1024 // (3 * 4 * d)
    for br in (256, 128, 64, 32, 16, 8):
        if br <= budget:
            return br
    return None


def _kernel(x_ref, res_ref, bias_ref, scale_ref, shift_ref, out_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    z = x + bias_ref[...].astype(jnp.float32) \
        + res_ref[...].astype(jnp.float32)
    mean = jnp.mean(z, axis=1, keepdims=True)
    c = z - mean
    var = jnp.mean(c * c, axis=1, keepdims=True)
    y = c * jax.lax.rsqrt(var + eps)
    y = y * scale_ref[...].astype(jnp.float32) \
        + shift_ref[...].astype(jnp.float32)
    out_ref[...] = y.astype(out_ref.dtype)


def _reference(x, residual, bias, scale, shift, eps):
    z = (x.astype(jnp.float32) + bias.astype(jnp.float32)
         + residual.astype(jnp.float32))
    mean = jnp.mean(z, axis=-1, keepdims=True)
    c = z - mean
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    y = c * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32) \
        + shift.astype(jnp.float32)
    return y.astype(x.dtype)


def _run(x, residual, bias, scale, shift, eps, interpret):
    r, d = x.shape
    block_r = _pick_block_r(d)
    pad = (-r) % block_r
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    rp = jnp.pad(residual, ((0, pad), (0, 0))) if pad else residual
    rows = xp.shape[0]
    kernel = functools.partial(_kernel, eps=eps)
    vec = lambda a: a.reshape(1, d)
    out = pl.pallas_call(
        kernel,
        grid=(rows // block_r,),
        in_specs=[
            pl.BlockSpec((block_r, d), lambda ri: (ri, _ZERO)),
            pl.BlockSpec((block_r, d), lambda ri: (ri, _ZERO)),
            pl.BlockSpec((1, d), lambda ri: (_ZERO, _ZERO)),
            pl.BlockSpec((1, d), lambda ri: (_ZERO, _ZERO)),
            pl.BlockSpec((1, d), lambda ri: (_ZERO, _ZERO)),
        ],
        out_specs=pl.BlockSpec((block_r, d), lambda ri: (ri, _ZERO)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=interpret,
    )(xp, rp, vec(bias), vec(scale), vec(shift))
    return out[:r]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def fused_bias_residual_layer_norm(x, residual, bias, scale, shift,
                                   eps=1e-5, interpret=False):
    """y = LN(x + bias + residual) * scale + shift.

    x/residual: [rows, d]; bias/scale/shift: [d]. Row blocks stream through
    VMEM; stats are computed in f32 regardless of input dtype.
    """
    return _run(x, residual, bias, scale, shift, eps, interpret)


def _vjp_fwd(x, residual, bias, scale, shift, eps, interpret):
    out = _run(x, residual, bias, scale, shift, eps, interpret)
    return out, (x, residual, bias, scale, shift)


def _vjp_bwd(eps, interpret, res, g):
    x, residual, bias, scale, shift = res
    _, vjp = jax.vjp(
        lambda xx, rr, bb, sc, sh: _reference(xx, rr, bb, sc, sh, eps),
        x, residual, bias, scale, shift)
    return vjp(g)


fused_bias_residual_layer_norm.defvjp(_vjp_fwd, _vjp_bwd)
