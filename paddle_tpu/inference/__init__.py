"""Inference API. Reference analog: paddle/fluid/inference/ —
`AnalysisPredictor` (api/analysis_predictor.h:95), `AnalysisConfig`
(api/paddle_analysis_config.h), zero-copy input/output handles
(`Predictor.get_input_handle().copy_from_cpu(...)`).

TPU-first: the reference's IR-analysis/fusion pass pipeline and TensorRT
subgraph capture are XLA's job — the saved artifact is jax.export StableHLO
(produced by paddle_tpu.jit.save / static.save_inference_model), and the
predictor is a thin handle-based wrapper so reference deployment code ports
unchanged.

Scope: `Predictor` replays ONE exported program per `run()` — right for
stateless single-model inference (classification, embedding, scoring)
and for porting reference `paddle_infer` call sites. For **batched
autoregressive GENERATION under live traffic** use
`paddle_tpu.serving.LLMEngine` instead: it is the engine behind the
reference's serving deployments rebuilt for TPU — continuous
(iteration-level) batching over a paged KV cache, ONE compiled
decode-step executable for every tenant mix (zero retraces as requests
join/leave), bucketed prefill, preempt-resume, and streaming `on_token`
callbacks::

    from paddle_tpu.serving import LLMEngine
    engine = LLMEngine(model, max_batch_size=8, block_size=16)
    outs = engine.generate(prompt_id_lists, max_new_tokens=64)

A `PredictorPool` of per-request predictors (the reference's serving
pattern) freezes batch composition for a request's lifetime; `LLMEngine`
re-forms the batch at every token boundary — that is the difference
between one-user latency and millions-of-users throughput. See the
README "Serving" section and `tools/serve_bench.py`."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType", "get_version", "DataType", "PredictorPool",
           "get_num_bytes_of_data_type", "convert_to_mixed_precision",
           "get_trt_compile_version", "get_trt_runtime_version",
           "_get_phi_kernel_name"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM = 3


class Config:
    """Holds the model path + knobs. GPU/IR/TensorRT toggles are accepted for
    API parity; on TPU they map to XLA behaviors that are always on."""

    def __init__(self, prog_file=None, params_file=None):
        self._model_path = prog_file
        self._params_file = params_file
        self._ir_optim = True
        self._memory_optim = True
        self._precision = PrecisionType.Float32
        self._threads = 1
        self._place = PlaceType.TPU

    # --- model location
    def set_prog_file(self, path):
        self._model_path = path

    def prog_file(self):
        return self._model_path

    def set_params_file(self, path):
        self._params_file = path

    def set_model(self, prog_file, params_file=None):
        self._model_path = prog_file
        self._params_file = params_file

    # --- parity knobs
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._place = PlaceType.GPU  # honored as "accelerator": TPU here

    def disable_gpu(self):
        self._place = PlaceType.CPU

    def enable_memory_optim(self, x=True):
        self._memory_optim = x

    def switch_ir_optim(self, x=True):
        self._ir_optim = x

    def set_cpu_math_library_num_threads(self, n):
        self._threads = n

    def enable_tensorrt_engine(self, **kw):
        pass  # XLA owns fusion on TPU

    def enable_mkldnn(self):
        pass

    def switch_use_feed_fetch_ops(self, x):
        pass

    def switch_specify_input_names(self, x=True):
        pass

    def precision_mode(self):
        return self._precision


class _IOHandle:
    """Zero-copy-style tensor handle (reference: ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._array = None

    def reshape(self, shape):
        if self._array is None:
            self._array = np.zeros(shape, np.float32)
        else:
            self._array = np.resize(self._array, shape)

    def copy_from_cpu(self, arr):
        self._array = np.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._array)

    def shape(self):
        return list(self._array.shape) if self._array is not None else []

    def type(self):
        return str(self._array.dtype) if self._array is not None else None


class Predictor:
    def __init__(self, config):
        from ..jit.api import load as jload, TranslatedLayer
        self._config = config
        if config.prog_file() is None:
            raise ValueError("Config has no model path; call set_prog_file")
        art = jload(config.prog_file())
        if not isinstance(art, TranslatedLayer):
            raise ValueError(
                f"{config.prog_file()} is not a paddle_tpu.jit artifact")
        if not art.has_forward:
            raise ValueError(
                "artifact has no compiled forward; re-save with input_spec")
        self._layer = art
        n_in = max(1, self._infer_num_inputs(art))
        self._inputs = {f"x{i}": _IOHandle(f"x{i}") for i in range(n_in)}
        self._outputs = {}

    @staticmethod
    def _infer_num_inputs(art):
        n_state = len(art._param_values)
        try:
            # exported signature: (values list, key); count of avals minus
            # params/buffers minus the rng key
            total = len(art._exported.in_avals)
            return max(1, total - n_state - 1)
        except Exception:
            return 1

    def get_input_names(self):
        return list(self._inputs.keys())

    def get_input_handle(self, name):
        return self._inputs.setdefault(name, _IOHandle(name))

    def get_output_names(self):
        return list(self._outputs.keys())

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Either pass arrays directly (returns list of np arrays) or use the
        handle API: copy_from_cpu -> run() -> copy_to_cpu."""
        if inputs is not None:
            args = [np.asarray(a) for a in inputs]
        else:
            args = [h.copy_to_cpu() for h in self._inputs.values()
                    if h._array is not None]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        arrays = [o.numpy() if isinstance(o, Tensor) else np.asarray(o)
                  for o in outs]
        self._outputs = {f"out{i}": _IOHandle(f"out{i}")
                         for i in range(len(arrays))}
        for h, a in zip(self._outputs.values(), arrays):
            h.copy_from_cpu(a)
        return arrays

    def clone(self):
        return Predictor(self._config)


def create_predictor(config):
    return Predictor(config)


def get_version():
    from .. import __version__
    return __version__


class DataType:
    """Reference paddle_infer.DataType enum."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


_DTYPE_BYTES = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
                DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
                DataType.BFLOAT16: 2}


def get_num_bytes_of_data_type(dtype):
    """Reference inference API helper."""
    return _DTYPE_BYTES[dtype]


def get_trt_compile_version():
    """No TensorRT on TPU (XLA owns inference compilation)."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    """Fluid op name -> phi kernel name (reference pybind helper). The op
    registry here is already phi-style, so names pass through."""
    return op_name


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Reference convert_to_mixed_precision: rewrite a saved model to
    mixed precision. TPU-native saved artifacts are StableHLO exports whose
    precision is chosen AT EXPORT (bf16 weights + jit) — re-export the
    layer with model.bfloat16() instead of rewriting the artifact."""
    raise NotImplementedError(
        "TPU inference artifacts fix precision at export: call "
        "model.bfloat16() before jit.save / save_inference_model instead "
        "of converting the saved file")


class PredictorPool:
    """Pool of Predictors sharing one config (reference
    paddle_infer.PredictorPool — serving worker pools). For generation
    workloads prefer `paddle_tpu.serving.LLMEngine`: one continuous
    batch instead of one frozen batch per pooled worker."""

    def __init__(self, config, size=1):
        self._predictors = [create_predictor(config)
                            for _ in range(max(int(size), 1))]

    def retrive(self, idx):
        return self._predictors[idx]

    retrieve = retrive          # reference spells it "retrive"

    def size(self):
        return len(self._predictors)
