"""paddle.hub — load models from a hubconf.py. Reference analog:
python/paddle/hapi/hub.py (list/help/load with github/gitee/local sources:
_get_cache_or_reload downloads "https://github.com/{owner}/{repo}/archive/
{branch}.zip" into hub_home/<normalized name>, extracts, and imports the
repo's hubconf.py entrypoints).

Full protocol parity: github/gitee sources resolve "owner/repo[:branch]",
download the archive into the hub cache (reused unless force_reload), and
import hubconf.py from the extracted tree; source='local' takes a directory
directly. In a no-egress environment remote sources fail at the download
step with a clear error — the cache path still works if pre-populated.
"""
from __future__ import annotations

import importlib.util
import os
import shutil
import sys
import zipfile

__all__ = ["list", "help", "load", "set_hub_home", "get_hub_home"]

_HUB_HOME = None
_HUBCONF = "hubconf.py"


def set_hub_home(path):
    """Override the hub cache directory (reference: HUB_DIR)."""
    global _HUB_HOME
    _HUB_HOME = path


def get_hub_home():
    return _HUB_HOME or os.environ.get(
        "PADDLE_HUB_HOME",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle", "hub"))


def _parse_repo(repo, source):
    """'owner/repo[:branch]' -> (owner, repo, branch, archive url)."""
    if ":" in repo:
        repo_part, branch = repo.split(":", 1)
    else:
        repo_part, branch = repo, "main"
    if repo_part.count("/") != 1:
        raise ValueError(
            f"remote repo must be 'owner/name[:branch]', got {repo!r}")
    owner, name = repo_part.split("/")
    host = "github.com" if source == "github" else "gitee.com"
    url = f"https://{host}/{owner}/{name}/archive/{branch}.zip"
    return owner, name, branch, url


def _safe_extract(zf, dest):
    """extractall with member-path validation (zip-slip guard): every
    member must land strictly inside `dest`."""
    dest_real = os.path.realpath(dest)
    for m in zf.namelist():
        target = os.path.realpath(os.path.join(dest, m))
        if not (target + os.sep).startswith(dest_real + os.sep):
            raise RuntimeError(f"archive member escapes extraction dir: "
                               f"{m!r}")
    zf.extractall(dest)


def _get_cache_or_reload(repo, source, force_reload):
    """Reference: hapi/hub.py _get_cache_or_reload — cache dir keyed by
    owner_name_branch; download+extract on miss or force_reload. The
    download lands in a temp dir and swaps in only on success, so
    force_reload never destroys the existing copy on a failed fetch."""
    import tempfile
    owner, name, branch, url = _parse_repo(repo, source)
    hub_home = get_hub_home()
    os.makedirs(hub_home, exist_ok=True)
    # collision-free cache key: path separators quoted, no lossy '-'/'_'
    # folding (quote('-') == '-', so 'my-repo' and 'my_repo' stay distinct)
    from urllib.parse import quote
    key = "_".join(quote(part, safe="") for part in (owner, name, branch))
    cache_dir = os.path.join(hub_home, key)
    if os.path.exists(cache_dir) and not force_reload:
        return cache_dir
    tmp = tempfile.mkdtemp(dir=hub_home, prefix=".fetch_")
    zip_path = os.path.join(tmp, "archive.zip")
    try:
        try:
            import urllib.request
            urllib.request.urlretrieve(url, zip_path)
        except Exception as e:
            raise RuntimeError(
                f"cannot download {url}: {e}. This environment may have no "
                "network egress — pre-populate the cache at "
                f"{cache_dir} (a checkout containing {_HUBCONF}) or use "
                "source='local'.") from e
        with zipfile.ZipFile(zip_path) as zf:
            roots = {n.split("/")[0] for n in zf.namelist() if n.strip("/")}
            if len(roots) != 1:
                raise RuntimeError(f"unexpected archive layout from {url}")
            _safe_extract(zf, tmp)
        extracted = os.path.join(tmp, roots.pop())
        # success: swap in atomically-ish, only now touching the old copy
        if os.path.exists(cache_dir):
            shutil.rmtree(cache_dir)
        os.rename(extracted, cache_dir)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return cache_dir


def _resolve(repo_dir, source, force_reload):
    if source == "local":
        return repo_dir
    if source not in ("github", "gitee"):
        raise ValueError(
            f"source must be 'github', 'gitee' or 'local', got {source!r}")
    return _get_cache_or_reload(repo_dir, source, force_reload)


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} under {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    return mod


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf.py."""
    mod = _load_hubconf(_resolve(repo_dir, source, force_reload))
    return [name for name in dir(mod)
            if callable(getattr(mod, name)) and not name.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    mod = _load_hubconf(_resolve(repo_dir, source, force_reload))
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate entrypoint `model` from the repo's hubconf.py."""
    resolved = _resolve(repo_dir, source, force_reload)
    mod = _load_hubconf(resolved)
    if not hasattr(mod, model):
        raise ValueError(
            f"{model!r} not in {resolved}/{_HUBCONF}; available: "
            f"{list(resolved)}")
    return getattr(mod, model)(**kwargs)
