"""paddle.hub — load models from a hubconf.py. Reference analog:
python/paddle/hapi/hub.py (list/help/load with github/gitee/local sources).

This environment has no network egress, so only source='local' is supported;
a hub repo is any directory with a hubconf.py exposing entrypoint callables
(functions not prefixed with '_').
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    return mod


def _check_source(source):
    if source != "local":
        raise ValueError(
            f"source={source!r} needs network access, which this environment "
            "does not have; use source='local' with a checked-out repo dir")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [name for name in dir(mod)
            if callable(getattr(mod, name)) and not name.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate entrypoint `model` from the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(
            f"{model!r} not in {repo_dir}/hubconf.py; available: "
            f"{list(repo_dir)}")
    return getattr(mod, model)(**kwargs)
