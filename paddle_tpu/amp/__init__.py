"""AMP package. Reference analog: python/paddle/amp/."""
from .auto_cast import auto_cast, amp_guard, decorate  # noqa: F401
from .grad_scaler import GradScaler  # noqa: F401
