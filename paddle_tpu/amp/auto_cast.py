"""AMP autocast. Reference analog: python/paddle/amp/auto_cast.py:21 and the
eager AMP pass in generated ad_funcs (eager/amp_utils.h).

TPU-first: bfloat16 is the native mixed-precision dtype — no loss scaling is
required (GradScaler is provided for API parity and is a near-no-op for bf16).
O1 = autocast white/black lists at op granularity; O2 = cast the whole model,
keep master weights in the optimizer.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

__all__ = ["auto_cast", "amp_guard", "amp_cast_inputs", "decorate",
           "WHITE_LIST", "BLACK_LIST"]

_state = threading.local()

# Op-level lists, mirroring the reference's O1 default lists
# (python/paddle/fluid/dygraph/amp/auto_cast.py AMP_WHITE_LIST / BLACK_LIST).
WHITE_LIST = {
    "matmul", "mm", "bmm", "conv2d", "conv1d", "conv3d", "conv2d_transpose",
    "einsum", "linear", "addmm",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "softmax", "log_softmax",
    "cross_entropy", "softmax_with_cross_entropy", "mean", "sum", "norm",
    "layer_norm", "batch_norm", "group_norm", "instance_norm", "reduce_sum",
    "cumsum", "pow", "square", "sigmoid_cross_entropy_with_logits",
    "binary_cross_entropy", "nll_loss", "l1_loss", "mse_loss", "smooth_l1_loss",
}


class _AmpState:
    __slots__ = ("enabled", "dtype", "level", "white", "black")

    def __init__(self, enabled, dtype, level, white, black):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.white = white
        self.black = black


def _stack():
    s = getattr(_state, "stack", None)
    if s is None:
        s = _state.stack = []
    return s


def current_amp_state():
    s = _stack()
    return s[-1] if s else None


class auto_cast:
    """`paddle.amp.auto_cast` context manager."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16"):
        if level not in ("O0", "O1", "O2"):
            raise ValueError(f"level must be O0/O1/O2, got {level}")
        from ..framework.dtype import to_jax_dtype
        white = set(WHITE_LIST)
        black = set(BLACK_LIST)
        if custom_white_list:
            white |= set(custom_white_list)
            black -= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
            white -= set(custom_black_list)
        self._st = _AmpState(enable and level != "O0", to_jax_dtype(dtype),
                             level, white, black)

    def __enter__(self):
        _stack().append(self._st)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


amp_guard = auto_cast


def amp_cast_inputs(op_name: str, tensors):
    """Called from op dispatch: cast float inputs per the active policy."""
    st = current_amp_state()
    if st is None or not st.enabled:
        return tensors
    if st.level == "O2":
        # pure low-precision except black list
        target = jnp.float32 if op_name in st.black else st.dtype
    else:
        if op_name in st.white:
            target = st.dtype
        elif op_name in st.black:
            target = jnp.float32
        else:
            return tensors
    out = []
    changed = False
    from ..ops._helpers import jnp_dtype
    for t in tensors:
        # dtype from chain metadata when the input is a deferred fusion
        # placeholder (ops/fusion.py): a no-cast decision must not force a
        # pending chain to materialize
        dt = jnp_dtype(t)
        if jnp.issubdtype(dt, jnp.floating) and dt != target:
            # cast the raw value and alias the producer's grad node: the
            # downstream op's VJP then emits grads in compute dtype, which
            # accumulate into the original tensor (standard AMP behavior)
            # (reading _value here forces a pending placeholder — the cast
            # is a real escape, the chain splits, numerics stay identical)
            from ..framework.core import Tensor
            v = t._value
            casted = Tensor(v.astype(target), stop_gradient=t.stop_gradient)
            casted._grad_node = t._grad_node
            casted._out_index = t._out_index
            if t._grad_node is None and not t.stop_gradient:
                t._ensure_grad_node()
                casted._grad_node = t._grad_node
                casted._out_index = t._out_index
            out.append(casted)
            changed = True
        else:
            out.append(t)
    return out if changed else tensors


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """`paddle.amp.decorate` — O2: cast model params to low precision.
    Master weights live in the optimizer accumulators (see optimizer)."""
    from ..framework.dtype import to_jax_dtype
    jd = to_jax_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            if m is None:
                continue
            for p in m.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._value = p._value.astype(jd)
    if optimizers is None:
        return models
    return models, optimizers
