"""GradScaler. Reference analog: python/paddle/amp/grad_scaler.py:26
(`step` :166, `unscale_` :251) over check_finite_and_unscale /
update_loss_scaling ops (paddle/fluid/operators/amp/).

TPU-first: bf16 training needs no loss scaling, so with bf16 autocast this is
a documented no-op passthrough. For fp16, the full dynamic loss-scaling state
machine is implemented (scale on loss, unscale+finite-check on grads, skip
step and shrink scale on overflow, grow after N good steps).

Fusion contract (PR 5, ops/guardian.py + ops/step_fusion.py): all scaler
state lives on DEVICE and the loss scale rides as a dispatch *input* — keyed
by aval, never by value — so a backoff changes nothing about the compiled
step and dynamic-loss-scaled loops promote to ONE fused executable. Under
`FLAGS_check_numerics` the skip-step decision is in-graph
(`where(finite, new, old)` inside the optimizer update), so `step()` never
syncs: a found-inf batch is a bitwise no-op update. Without the guardian the
legacy semantics are kept — one host sync of the found-inf scalar per step
and a Python-level skip (which is also why such loops cannot whole-step
fuse; the step recorder attributes them as `mid_step_peek`).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["GradScaler"]


def _scale_mul(v, s):
    """Loss scaling as a keyable dispatched op: the scale arrives as an
    input aval (hoisted scalar), not a closure constant."""
    return v * s.astype(v.dtype)


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        # device scalars after the first transition; python numbers until
        # then (constructing jnp arrays here would touch the backend at
        # import-adjacent time)
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        # set by a fused whole-step fire (ops/step_fusion.py): the
        # executable already computed (found_inf, scale', good', bad');
        # update() commits it instead of re-running the transition
        self._fused_next = None

    # -- fused-step integration helpers -------------------------------------
    def _consts(self):
        """The constants a fused step executable bakes in (snapshot-verified
        at every fire; a change kills the promoted program)."""
        return (bool(self._enable), bool(self._dynamic),
                float(self._incr_ratio), float(self._decr_ratio),
                int(self._incr_every_n_steps),
                int(self._decr_every_n_nan_or_inf))

    def _state_arrays(self):
        return (jnp.asarray(self._scale, jnp.float32),
                jnp.asarray(self._good_steps, jnp.int32),
                jnp.asarray(self._bad_steps, jnp.int32))

    # -- public API ----------------------------------------------------------
    def scale(self, var):
        if not self._enable:
            return var
        from ..framework.core import Tensor
        from ..ops import guardian
        from ..ops.dispatch import call_op
        # AMP thread: fp16 forward overflow is expected and rescued by the
        # found-inf/skip-step path, so the guardian attributes non-finite
        # forward outputs instead of raising
        guardian.mark_scaler_active()
        s = Tensor(jnp.asarray(self._scale, jnp.float32),
                   stop_gradient=True, name="loss_scale")
        return call_op("scale_loss", _scale_mul, (var, s))

    def unscale_(self, optimizer):
        """check_finite_and_unscale analog: divide grads by scale, record
        whether any grad is non-finite — as ONE device scalar, no host
        sync here (the legacy step() path syncs it once; the guardian path
        never does)."""
        if not self._enable:
            return
        if self._unscaled:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update()")
        from ..ops import guardian
        grads = [p.grad for p in optimizer._parameter_list
                 if p.grad is not None]
        if grads:
            # reading ._value forces any pending fused-step placeholder,
            # which splits the replay first (mid_step_peek) — grads are
            # real past this line
            gvals = [g._value for g in grads]
            inv = jnp.asarray(1.0, jnp.float32) \
                / jnp.asarray(self._scale, jnp.float32)
            self._found_inf = jnp.logical_not(guardian.finite_all(gvals))
            for g, gv in zip(grads, gvals):
                g._value = gv * inv.astype(gv.dtype)
        else:
            self._found_inf = False
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        from ..ops import guardian
        from ..ops.step_fusion import STEP as _step_fusion
        guardian.mark_scaler_active()
        if _step_fusion.on_scaler_step(self, optimizer):
            # a pending whole-step replay matched: ONE fused executable
            # already unscaled, finite-checked, where()-updated the
            # params/slots and advanced the loss-scale state
            self._unscaled = False
            guardian.maybe_flush()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        self._unscaled = False
        if guardian.skip_step_enabled():
            # in-graph skip-step rescue: the optimizer update applies
            # where(finite, new, old), so step() runs unconditionally
            # (and advances the step counter) with no host sync — a
            # found-inf batch is a bitwise no-op on params and slots
            optimizer.step()
        elif not bool(np.asarray(self._found_inf)):
            # legacy semantics: one host sync, Python-level skip
            optimizer.step()

    def update(self):
        """update_loss_scaling analog — the state transition runs on
        device (guardian.update_scaler_state) or is committed from the
        fused step executable's outputs; nothing here syncs."""
        if not self._enable:
            return
        fused = self._fused_next
        self._fused_next = None
        if not self._dynamic:
            self._found_inf = False
            return
        from ..ops import guardian
        if fused is not None:
            # the fused fire already traced the identical transition in;
            # its backoff (if any) was attributed at the fire
            _found, s2, g2, b2 = fused
        else:
            scale, good, bad = self._state_arrays()
            s2, g2, b2 = guardian.update_scaler_state(
                scale, good, bad, self._found_inf, self._incr_ratio,
                self._decr_ratio, self._incr_every_n_steps,
                self._decr_every_n_nan_or_inf)
            if guardian.enabled():
                guardian.note_scaler(scale, s2)
        self._scale, self._good_steps, self._bad_steps = s2, g2, b2
        self._found_inf = False

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return float(np.asarray(self._scale))

    def state_dict(self):
        return {"scale": float(np.asarray(self._scale)),
                "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
                "good_steps": int(np.asarray(self._good_steps)),
                "bad_steps": int(np.asarray(self._bad_steps))}

    def load_state_dict(self, state):
        self._scale = float(np.asarray(state["scale"]))
        self._good_steps = int(state.get("good_steps", 0))
        self._bad_steps = int(state.get("bad_steps", 0))
        self._found_inf = False
        self._unscaled = False
        self._fused_next = None
