"""GradScaler. Reference analog: python/paddle/amp/grad_scaler.py:26
(`step` :166, `unscale_` :251) over check_finite_and_unscale /
update_loss_scaling ops (paddle/fluid/operators/amp/).

TPU-first: bf16 training needs no loss scaling, so with bf16 autocast this is
a documented no-op passthrough. For fp16, the full dynamic loss-scaling state
machine is implemented (scale on loss, unscale+finite-check on grads, skip
step and shrink scale on overflow, grow after N good steps).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["GradScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """check_finite_and_unscale analog: divide grads by scale, record
        whether any grad is non-finite."""
        if not self._enable or self._unscaled:
            return
        found = False
        inv = 1.0 / self._scale
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._value * jnp.asarray(inv, p.grad._value.dtype)
            found = found or bool(~jnp.isfinite(g).all())
            p.grad._value = g
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        """update_loss_scaling analog."""
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
