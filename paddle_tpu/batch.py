"""`paddle.batch` — wrap a sample reader into a mini-batch reader.

Reference analog: python/paddle/batch.py:18 (the legacy reader-decorator
API kept for BC; new code uses paddle.io.DataLoader).
"""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Return a reader yielding lists of `batch_size` samples from `reader`.

    `reader` is a no-arg callable returning an iterable of samples (the
    classic paddle reader protocol).
    """
    if batch_size <= 0:
        raise ValueError(
            f"batch_size should be a positive integer, got {batch_size}")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
