"""Native runtime core: TCPStore, ThreadPool, BoundedQueue, host tracer.

Reference analog: the C++ runtime under paddle/fluid/distributed/store/
(TCPStore), framework/new_executor/workqueue/, operators/reader/
(buffered_reader), and platform/profiler/host_event_recorder.h, exposed to
Python via pybind (`core.TCPStore` etc.). Here the native library is built
from csrc/ by g++ at first use and bound via ctypes; every class has a
pure-Python fallback so the framework works without a toolchain.
"""
from __future__ import annotations

import ctypes
import queue as _pyqueue
import socket
import threading
import time

import numpy as np

from ._build import load_library, build_library

__all__ = ["TCPStore", "ThreadPool", "BoundedQueue", "native_available",
           "host_tracer", "parallel_collate"]


def native_available():
    return load_library() is not None


# --------------------------------------------------------------------- store
class TCPStore:
    """Socket KV store for rendezvous (reference: store/tcp_store.h:117).

    host_name/port point at the master; the rank with is_master=True also
    runs the server thread. API: set/get/add/wait/delete_key + barrier.
    """

    def __init__(self, host_name="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=30.0):
        self._lib = load_library()
        self._server = None
        self._world_size = world_size
        self._timeout_ms = int(timeout * 1000)
        self._barrier_round = 0
        if self._lib is None:
            raise RuntimeError(
                "native core unavailable (no g++?); TCPStore requires the "
                "native runtime — see paddle_tpu/core/_build.py")
        if is_master:
            actual = ctypes.c_int(0)
            self._server = self._lib.pd_store_server_start(
                port, ctypes.byref(actual))
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = actual.value
        self.host = host_name
        self.port = port
        self._client = self._lib.pd_store_client_connect(
            host_name.encode(), port, self._timeout_ms)
        if not self._client:
            if self._server:
                self._lib.pd_store_server_stop(self._server)
            raise RuntimeError(
                f"TCPStore: cannot connect to {host_name}:{port}")

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        buf = (ctypes.c_uint8 * len(value)).from_buffer_copy(value) \
            if value else None
        rc = self._lib.pd_store_set(self._client, key.encode(), buf,
                                    len(value))
        if rc != 0:
            raise RuntimeError(f"TCPStore.set({key!r}) failed: {rc}")

    def get(self, key, wait=True):
        if wait:
            self.wait([key])
        # each pd_store_get is ONE RPC whose returned length matches the bytes
        # it copied; loop growing the buffer until the whole value fits, so a
        # value overwritten with a longer one mid-call is never truncated
        buf_len = 256
        while True:
            buf = (ctypes.c_uint8 * buf_len)()
            n = self._lib.pd_store_get(self._client, key.encode(), buf,
                                       buf_len)
            if n == -1:
                raise KeyError(key)
            if n < 0:
                raise RuntimeError(f"TCPStore.get({key!r}) transport error")
            if n <= buf_len:
                return bytes(buf[:int(n)])
            buf_len = int(n)

    def add(self, key, value=1):
        result = ctypes.c_int64(0)
        rc = self._lib.pd_store_add(self._client, key.encode(), int(value),
                                    ctypes.byref(result))
        if rc != 0:
            raise RuntimeError(f"TCPStore.add({key!r}) transport error")
        return int(result.value)

    def wait(self, keys, timeout=None):
        # protocol: 0 = wait forever, so a zero/rounded-to-zero timeout must
        # still send >=1ms to keep "timeout=0" meaning an immediate poll
        tmo = self._timeout_ms if timeout is None else \
            max(1, int(timeout * 1000))
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            rc = self._lib.pd_store_wait(self._client, k.encode(), tmo)
            if rc == -2:
                raise TimeoutError(f"TCPStore.wait({k!r}) timed out")
            if rc != 0:
                raise RuntimeError(f"TCPStore.wait({k!r}) failed: {rc}")

    def delete_key(self, key):
        rc = self._lib.pd_store_delete(self._client, key.encode())
        if rc < 0:
            raise RuntimeError(f"TCPStore.delete_key({key!r}) transport error")
        return bool(rc)

    def barrier(self, tag=""):
        """All world_size participants block until everyone arrives."""
        self._barrier_round += 1
        key = f"__barrier/{tag}/{self._barrier_round}"
        arrived = self.add(key, 1)
        if arrived >= self._world_size:
            self.set(key + "/done", b"1")
        self.wait([key + "/done"])

    def __del__(self):
        lib, client, server = getattr(self, "_lib", None), \
            getattr(self, "_client", None), getattr(self, "_server", None)
        if lib is None:
            return
        try:
            if client:
                lib.pd_store_client_close(client)
            if server:
                lib.pd_store_server_stop(server)
        except Exception:
            pass


# --------------------------------------------------------------------- pool
class ThreadPool:
    """Native threadpool (reference: new_executor/workqueue). Used for
    GIL-free parallel memcpy in batch collation."""

    def __init__(self, num_threads):
        self._lib = load_library()
        self._native = None
        if self._lib is not None:
            self._native = self._lib.pd_pool_create(num_threads)
        self._n = num_threads

    @property
    def is_native(self):
        return self._native is not None

    def parallel_memcpy(self, dsts, srcs, sizes):
        """Copy srcs[i] -> dsts[i] (ctypes pointers / ints) concurrently."""
        n = len(dsts)
        if self._native is not None:
            DA = (ctypes.c_void_p * n)(*dsts)
            SA = (ctypes.c_void_p * n)(*srcs)
            ZA = (ctypes.c_uint64 * n)(*sizes)
            self._lib.pd_pool_parallel_memcpy(self._native, DA, SA, ZA, n)
        else:
            for d, s, z in zip(dsts, srcs, sizes):
                ctypes.memmove(d, s, z)

    def close(self):
        if self._native is not None:
            self._lib.pd_pool_destroy(self._native)
            self._native = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


_collate_pool = None
_collate_lock = threading.Lock()


def _get_collate_pool():
    global _collate_pool
    with _collate_lock:
        if _collate_pool is None:
            _collate_pool = ThreadPool(4)
        return _collate_pool


# parallel stacking pays for itself only on big batches; below this, np.stack
# wins on dispatch overhead
_COLLATE_MIN_BYTES = 1 << 20


def parallel_collate(arrays):
    """np.stack(arrays) with the copies done by the native threadpool.
    Reference analog: buffered_reader.cc assembling device batches."""
    first = np.ascontiguousarray(arrays[0])
    total = first.nbytes * len(arrays)
    if total < _COLLATE_MIN_BYTES or not native_available() or \
            any(a.shape != first.shape or a.dtype != first.dtype
                for a in arrays):
        # np.stack raises the proper error for ragged / mixed-dtype batches
        return np.stack(arrays)
    out = np.empty((len(arrays),) + first.shape, dtype=first.dtype)
    pool = _get_collate_pool()
    step = first.nbytes
    base = out.ctypes.data
    contig = [np.ascontiguousarray(a) for a in arrays]
    dsts = [base + i * step for i in range(len(contig))]
    srcs = [a.ctypes.data for a in contig]
    sizes = [step] * len(contig)
    pool.parallel_memcpy(dsts, srcs, sizes)
    return out


# --------------------------------------------------------------------- queue
class BoundedQueue:
    """Bounded blocking queue (reference: lod_tensor_blocking_queue.h).
    Items are arbitrary Python objects; the blocking/wakeup machinery is
    native so producers/consumers don't contend on the GIL."""

    def __init__(self, capacity):
        self._lib = load_library()
        self._native = None
        self._objs = {}
        self._obj_lock = threading.Lock()
        self._next_token = 0
        if self._lib is not None:
            self._native = self._lib.pd_queue_create(capacity)
        else:
            self._pyq = _pyqueue.Queue(maxsize=capacity)
            self._closed = False

    @property
    def is_native(self):
        return self._native is not None

    def push(self, obj, timeout=None):
        if self._native is None:
            # mirror the native contract: return False once closed instead of
            # blocking forever on a full queue nobody will drain
            deadline = None if timeout is None else \
                time.monotonic() + timeout
            while True:
                if self._closed:
                    return False
                try:
                    self._pyq.put(obj, timeout=0.05)
                    return True
                except _pyqueue.Full:
                    if deadline is not None and \
                            time.monotonic() >= deadline:
                        raise
        with self._obj_lock:
            token = self._next_token
            self._next_token += 1
            self._objs[token] = obj
        tmo = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.pd_queue_push(self._native, token, tmo)
        if rc != 0:
            with self._obj_lock:
                self._objs.pop(token, None)
            if rc == -1:
                raise _pyqueue.Full()
            return False  # closed
        return True

    def pop(self, timeout=None):
        """Returns the object; raises queue.Empty on timeout, StopIteration
        when closed and drained."""
        if self._native is None:
            if self._closed and self._pyq.empty():
                raise StopIteration
            try:
                item = self._pyq.get(timeout=timeout)
            except _pyqueue.Empty:
                if self._closed:
                    raise StopIteration from None
                raise
            if item is _CLOSE_SENTINEL:
                self._closed = True
                raise StopIteration
            return item
        token = ctypes.c_uint64(0)
        tmo = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.pd_queue_pop(self._native, ctypes.byref(token), tmo)
        if rc == -1:
            raise _pyqueue.Empty()
        if rc == -2:
            raise StopIteration
        with self._obj_lock:
            return self._objs.pop(token.value)

    def close(self):
        if self._native is None:
            self._closed = True
            try:
                self._pyq.put_nowait(_CLOSE_SENTINEL)
            except _pyqueue.Full:
                pass
            return
        self._lib.pd_queue_close(self._native)

    def qsize(self):
        if self._native is None:
            return self._pyq.qsize()
        return int(self._lib.pd_queue_size(self._native))

    def __del__(self):
        # close first so any thread still blocked in push/pop wakes and
        # returns before the native queue (mutex/condvars) is freed. Owners
        # with producer threads must join them before dropping the queue
        # (see io.dataloader._PrefetchIterator.close).
        try:
            if getattr(self, "_native", None) is not None:
                self._lib.pd_queue_close(self._native)
                self._lib.pd_queue_destroy(self._native)
                self._native = None
        except Exception:
            pass


class _Sentinel:
    pass


_CLOSE_SENTINEL = _Sentinel()


# -------------------------------------------------------------------- tracer
class _HostTracer:
    """Thin wrapper over the native host event recorder (reference:
    platform/profiler/host_event_recorder.h). Used by paddle_tpu.profiler.
    The library loads lazily on first use so `import paddle_tpu` never
    triggers the g++ build."""

    def __init__(self):
        self._name_cache = {}

    @property
    def _lib(self):
        return load_library()

    @property
    def is_native(self):
        return self._lib is not None

    def enable(self, on=True):
        if self._lib is not None:
            self._lib.pd_trace_enable(1 if on else 0)

    def enabled(self):
        return self._lib is not None and \
            bool(self._lib.pd_trace_is_enabled())

    def name_id(self, name):
        nid = self._name_cache.get(name)
        if nid is None:
            nid = self._lib.pd_trace_register_name(name.encode())
            self._name_cache[name] = nid
        return nid

    def now_ns(self):
        if self._lib is not None:
            return int(self._lib.pd_trace_now_ns())
        return time.perf_counter_ns()

    def span(self, name, begin_ns, end_ns):
        if self._lib is not None:
            self._lib.pd_trace_span(self.name_id(name), begin_ns, end_ns)

    def harvest(self):
        """Returns list of (name, begin_ns, end_ns, tid)."""
        if self._lib is None:
            return []
        pending = int(self._lib.pd_trace_pending())
        if pending == 0:
            return []
        buf = (ctypes.c_uint64 * (pending * 4))()
        n = int(self._lib.pd_trace_harvest(buf, pending))
        out = []
        name_buf = ctypes.create_string_buffer(512)
        id2name = {}
        for i in range(n):
            nid = int(buf[i * 4])
            if nid not in id2name:
                ln = self._lib.pd_trace_name(nid, name_buf, 512)
                id2name[nid] = name_buf.value.decode() if ln >= 0 else str(nid)
            out.append((id2name[nid], int(buf[i * 4 + 1]),
                        int(buf[i * 4 + 2]), int(buf[i * 4 + 3])))
        return out


host_tracer = _HostTracer()


def find_free_port():
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
