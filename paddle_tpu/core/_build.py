"""Build the native runtime library (csrc/ -> libpaddle_tpu_core.so).

Reference analog: the reference compiles its runtime with CMake into
`libpaddle` (python/setup.py.in bundles it); here the native surface is small
enough that a direct g++ invocation at first import (cached by source mtime)
replaces the build system. Falls back gracefully: importers must handle
load_library() returning None and use pure-Python equivalents.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_LIB = None
_TRIED = False

_SRC_FILES = ("tcp_store.cc", "workqueue.cc", "host_tracer.cc",
              "ckpt_writer.cc")


def _csrc_dir():
    """csrc/ in the source tree (repo root) or bundled in the wheel
    (paddle_tpu/csrc, packaged by setup.py)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_csrc = os.path.join(os.path.dirname(pkg), "csrc")
    if os.path.isdir(repo_csrc):
        return repo_csrc
    return os.path.join(pkg, "csrc")


def _prebuilt_path():
    """Wheel builds ship the compiled library next to this module."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "libpaddle_tpu_core.so")


def _cache_dir():
    d = os.environ.get("PADDLE_TPU_CACHE",
                       os.path.join(tempfile.gettempdir(),
                                    "paddle_tpu_native"))
    os.makedirs(d, exist_ok=True)
    return d


def _needs_rebuild(lib_path, sources):
    if not os.path.exists(lib_path):
        return True
    lib_mtime = os.path.getmtime(lib_path)
    return any(os.path.getmtime(s) > lib_mtime for s in sources)


def build_library(verbose=False):
    """Compile csrc/*.cc into a shared library; returns path or None.
    A library prebuilt by the wheel (setup.py BuildNative) wins outright."""
    pre = _prebuilt_path()
    if os.path.exists(pre):
        return pre
    csrc = _csrc_dir()
    sources = [os.path.join(csrc, f) for f in _SRC_FILES]
    if not all(os.path.exists(s) for s in sources):
        return None
    lib_path = os.path.join(_cache_dir(), "libpaddle_tpu_core.so")
    if not _needs_rebuild(lib_path, sources):
        return lib_path
    # compile to a private temp name and atomically rename so a concurrent
    # process never dlopens a half-written library
    tmp_path = lib_path + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
           "-o", tmp_path] + sources + ["-lpthread"]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if res.returncode != 0:
        if verbose:
            print("paddle_tpu native build failed:\n" + res.stderr)
        return None
    try:
        os.replace(tmp_path, lib_path)
    except OSError:
        return None
    return lib_path


def load_library():
    """Build (if needed) and dlopen the native library. Returns the ctypes
    CDLL or None when no toolchain is available."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
        return None
    path = build_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None

    c = ctypes
    lib.pd_store_server_start.restype = c.c_void_p
    lib.pd_store_server_start.argtypes = [c.c_int, c.POINTER(c.c_int)]
    lib.pd_store_server_stop.argtypes = [c.c_void_p]
    lib.pd_store_client_connect.restype = c.c_void_p
    lib.pd_store_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pd_store_client_close.argtypes = [c.c_void_p]
    lib.pd_store_set.restype = c.c_int64
    lib.pd_store_set.argtypes = [c.c_void_p, c.c_char_p,
                                 c.POINTER(c.c_uint8), c.c_uint32]
    lib.pd_store_get.restype = c.c_int64
    lib.pd_store_get.argtypes = [c.c_void_p, c.c_char_p,
                                 c.POINTER(c.c_uint8), c.c_uint32]
    lib.pd_store_add.restype = c.c_int64
    lib.pd_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64,
                                 c.POINTER(c.c_int64)]
    lib.pd_store_wait.restype = c.c_int64
    lib.pd_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.pd_store_delete.restype = c.c_int64
    lib.pd_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    lib.pd_store_ping.restype = c.c_int64
    lib.pd_store_ping.argtypes = [c.c_void_p]

    lib.pd_pool_create.restype = c.c_void_p
    lib.pd_pool_create.argtypes = [c.c_int]
    lib.pd_pool_destroy.argtypes = [c.c_void_p]
    lib.pd_pool_parallel_memcpy.argtypes = [
        c.c_void_p, c.POINTER(c.c_void_p), c.POINTER(c.c_void_p),
        c.POINTER(c.c_uint64), c.c_int]

    lib.pd_queue_create.restype = c.c_void_p
    lib.pd_queue_create.argtypes = [c.c_uint64]
    lib.pd_queue_destroy.argtypes = [c.c_void_p]
    lib.pd_queue_close.argtypes = [c.c_void_p]
    lib.pd_queue_push.restype = c.c_int
    lib.pd_queue_push.argtypes = [c.c_void_p, c.c_uint64, c.c_int64]
    lib.pd_queue_pop.restype = c.c_int
    lib.pd_queue_pop.argtypes = [c.c_void_p, c.POINTER(c.c_uint64), c.c_int64]
    lib.pd_queue_size.restype = c.c_uint64
    lib.pd_queue_size.argtypes = [c.c_void_p]

    lib.pd_trace_register_name.restype = c.c_uint32
    lib.pd_trace_register_name.argtypes = [c.c_char_p]
    lib.pd_trace_enable.argtypes = [c.c_int]
    lib.pd_trace_is_enabled.restype = c.c_int
    lib.pd_trace_now_ns.restype = c.c_uint64
    lib.pd_trace_span.argtypes = [c.c_uint32, c.c_uint64, c.c_uint64]
    lib.pd_trace_harvest.restype = c.c_uint64
    lib.pd_trace_harvest.argtypes = [c.POINTER(c.c_uint64), c.c_uint64]
    lib.pd_trace_pending.restype = c.c_uint64
    lib.pd_trace_name.restype = c.c_int64
    lib.pd_trace_name.argtypes = [c.c_uint32, c.c_char_p, c.c_uint64]

    _LIB = lib
    return _LIB
