"""paddle.cost_model — per-op cost estimates for plan search.

Reference analog: python/paddle/cost_model/cost_model.py (91 LoC): builds a
probe program, profiles it, and serves static per-op times from
static_op_benchmark.json (GPU microbenchmark table) to the auto-parallel
tuner.

TPU-native: profile_measure really times executor runs (wall clock around
the compiled program — XLA owns the intra-program schedule), and the static
table carries analytic TPU estimates derived from FLOPs/bytes at v5e peak
(197 bf16 TFLOP/s, 819 GB/s HBM) — the same roofline the auto_parallel
planner costs plans with (paddle_tpu/distributed/auto_parallel/planner).
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["CostModel"]

# analytic per-op microsecond estimates at a canonical config (batch 32),
# keyed like the reference's static_op_benchmark.json entries
_STATIC_COST_DATA = [
    {"op": "matmul", "config": "float32 [32,1024]x[1024,1024]",
     "paddle_tpu_time": 0.34, "paddle_tpu_time_backward": 0.68},
    {"op": "matmul_v2", "config": "float32 [32,1024]x[1024,1024]",
     "paddle_tpu_time": 0.34, "paddle_tpu_time_backward": 0.68},
    {"op": "softmax", "config": "float32 [32,1024]",
     "paddle_tpu_time": 0.16, "paddle_tpu_time_backward": 0.24},
    {"op": "relu", "config": "float32 [32,1024]",
     "paddle_tpu_time": 0.08, "paddle_tpu_time_backward": 0.08},
    {"op": "layer_norm", "config": "float32 [32,1024]",
     "paddle_tpu_time": 0.20, "paddle_tpu_time_backward": 0.40},
    {"op": "embedding", "config": "float32 [32,1024] vocab 50304",
     "paddle_tpu_time": 0.25, "paddle_tpu_time_backward": 0.50},
    {"op": "elementwise_add", "config": "float32 [32,1024]",
     "paddle_tpu_time": 0.08, "paddle_tpu_time_backward": 0.08},
    {"op": "c_allreduce_sum", "config": "float32 4MB ring over ICI",
     "paddle_tpu_time": 18.0, "paddle_tpu_time_backward": 18.0},
]


class CostModel:
    """Reference cost_model.py:23."""

    def __init__(self):
        self._static_cost_data = None

    def build_program(self):
        """A tiny probe program (reference cost_model.py:27 builds
        X->fc(10)->mean under program_guard; here programs are callables
        the static Executor invokes — the main program is one jitted
        fc+mean step)."""
        import jax
        import jax.numpy as jnp
        from ..nn.layer.common import Linear

        layer = Linear(1, 10)

        def startup_program():
            return []

        @jax.jit
        def _fwd(x, w, b):
            return jnp.mean(x @ w + b)

        def main_program(X):
            out = _fwd(jnp.asarray(X, jnp.float32),
                       layer.weight._value, layer.bias._value)
            return np.asarray(out)

        return startup_program, main_program

    def profile_measure(self, startup_program, main_program, device="tpu",
                        fetch_cost_list=("time",)):
        """Run the program under the executor and return measured wall-time
        cost (reference cost_model.py:46 wraps the C++ profiler; on TPU the
        compiled program is the scheduling unit, so program wall time IS
        the cost datum; per-op splits come from the profiler's xplane)."""
        from .. import static
        exe = static.Executor()
        exe.run(startup_program)
        x = np.random.random(size=(10, 1)).astype("float32")
        exe.run(main_program, feed={"X": x}, fetch_list=[])  # compile
        t0 = time.perf_counter()
        exe.run(main_program, feed={"X": x}, fetch_list=[])
        elapsed = time.perf_counter() - t0
        return {"time": elapsed * 1e3, "device": device}

    def static_cost_data(self):
        """Reference cost_model.py:65 loads static_op_benchmark.json."""
        self._static_cost_data = _STATIC_COST_DATA
        return self._static_cost_data

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        """Reference cost_model.py:75."""
        if op_name is None:
            raise ValueError(
                "op_name should not be empty when you want to get static "
                "op time")
        if self._static_cost_data is None:
            self.static_cost_data()
        op_cost = {}
        for op_data in self._static_cost_data:
            if op_data["op"] == op_name and dtype in op_data["config"]:
                key = "paddle_tpu_time" if forward else \
                    "paddle_tpu_time_backward"
                op_cost["op_time"] = op_data[key]
                op_cost["config"] = op_data["config"]
        return op_cost
