"""Audio IO backends (reference: python/paddle/audio/backends — wave_backend
default, soundfile optional). WAV via the stdlib `wave` module."""
from .wave_backend import load, info, save, AudioInfo  # noqa: F401
from . import wave_backend  # noqa: F401


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            "only the stdlib wave_backend is bundled (soundfile is an "
            "optional dependency in the reference too)")


__all__ = ["load", "info", "save", "list_available_backends",
           "get_current_backend", "set_backend"]
