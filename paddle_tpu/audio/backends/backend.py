from .wave_backend import load, info, save, AudioInfo  # noqa: F401
