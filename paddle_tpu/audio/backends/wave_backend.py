"""WAV file IO (reference: audio/backends/wave_backend.py over the stdlib
wave module — 16-bit PCM)."""
from __future__ import annotations

import wave as _wave

import numpy as np

from ...framework.core import Tensor

__all__ = ["AudioInfo", "load", "info", "save"]


class AudioInfo:
    """Reference backend.py AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath, format=None):
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8, "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True, format=None):
    """Returns (waveform Tensor, sample_rate): [C, T] when channels_first
    (reference wave_backend.load)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(int(frame_offset))
        n = f.getnframes() - int(frame_offset) if num_frames < 0 \
            else int(num_frames)
        raw = f.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, np.int16).astype(np.float32)
        if normalize:
            data = data / 32768.0
    elif width == 1:
        data = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128)
        if normalize:
            data = data / 128.0
    else:
        raise ValueError(f"unsupported sample width {width} bytes")
    data = data.reshape(-1, nch)
    wav = data.T if channels_first else data
    return Tensor(wav), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16):
    if bits_per_sample != 16:
        raise ValueError("wave_backend saves 16-bit PCM only (reference "
                         "limitation)")
    arr = np.asarray(src._value if isinstance(src, Tensor) else src,
                     np.float32)
    if channels_first:
        arr = arr.T                      # -> [T, C]
    pcm = np.clip(arr * 32768.0, -32768, 32767).astype("<i2")
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1] if arr.ndim == 2 else 1)
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
