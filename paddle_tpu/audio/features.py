"""Audio feature layers. Reference analog:
python/paddle/audio/features/layers.py (Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC over the stft/frame ops).

TPU-first: framing is a strided gather and the whole feature pipeline is a
jit-friendly chain (rfft -> |.|^p -> mel matmul -> log/dct), so XLA fuses it
into a few kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer_base import Layer
from ..ops._helpers import ensure_tensor, call_op
from .functional import (compute_fbank_matrix, create_dct, get_window,
                         power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length, hop_length, center=True, pad_mode="reflect"):
    """x: [..., T] -> [..., frame_length, n_frames]."""
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(frame_length // 2,
                                          frame_length // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    t = x.shape[-1]
    n_frames = 1 + (t - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[:, None]
           + hop_length * jnp.arange(n_frames)[None, :])
    return x[..., idx]


def _stft(x, n_fft, hop_length, win, center, pad_mode):
    frames = _frame(x, n_fft, hop_length, center, pad_mode)
    frames = frames * win[:, None]
    return jnp.fft.rfft(frames, axis=-2)


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = get_window(window, self.win_length, dtype=dtype)._value
        if self.win_length < n_fft:  # center-pad window up to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lpad, n_fft - self.win_length - lpad))
        self.window = w

    def forward(self, x):
        x = ensure_tensor(x)

        def fn(v):
            spec = _stft(v, self.n_fft, self.hop_length, self.window,
                         self.center, self.pad_mode)
            return jnp.abs(spec) ** self.power
        return call_op("spectrogram", fn, (x,))


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype)
        self.n_mels = n_mels
        self.fbank = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm, dtype)._value

    def forward(self, x):
        spec = self._spectrogram(x)

        def fn(v):
            return jnp.einsum("mf,...ft->...mt", self.fbank, v)
        return call_op("mel_spectrogram", fn, (spec,))


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct = create_dct(n_mfcc, n_mels, dtype=dtype)._value

    def forward(self, x):
        logmel = self._log_melspectrogram(x)

        def fn(v):
            return jnp.einsum("mk,...mt->...kt", self.dct, v)
        return call_op("mfcc", fn, (logmel,))
