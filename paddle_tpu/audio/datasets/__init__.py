"""Audio datasets (reference: python/paddle/audio/datasets — TESS emotional
speech, ESC50 environmental sounds). No-egress synthetic fallback: class-
correlated sine mixtures with the real label spaces."""
from __future__ import annotations

import numpy as np

from ...io.dataset import Dataset

__all__ = ["TESS", "ESC50"]


class _SyntheticAudio(Dataset):
    N_TRAIN = 128
    N_TEST = 32
    SR = 16000
    DUR = 0.25

    def __init__(self, mode="train", feat_type="raw", seed_offset=0,
                 **feat_kwargs):
        self.mode = mode
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        rng = np.random.default_rng(
            (0 if mode in ("train", "dev") else 1) + seed_offset)
        n = self.N_TRAIN if mode in ("train", "dev") else self.N_TEST
        t = np.arange(int(self.SR * self.DUR)) / self.SR
        self.labels = rng.integers(0, self.N_CLASSES, n).astype(np.int64)
        base = 200.0
        self.waves = np.stack([
            np.sin(2 * np.pi * (base + 50.0 * lab) * t)
            + 0.05 * rng.standard_normal(t.shape)
            for lab in self.labels]).astype(np.float32)

    def _features(self, wav):
        if self.feat_type == "raw":
            return wav
        from .. import features as F
        from ...framework.core import Tensor
        import jax.numpy as jnp
        x = Tensor(jnp.asarray(wav[None]))
        if self.feat_type == "spectrogram":
            return np.asarray(F.Spectrogram(**self.feat_kwargs)(x)._value)[0]
        if self.feat_type == "melspectrogram":
            return np.asarray(
                F.MelSpectrogram(sr=self.SR, **self.feat_kwargs)(x)._value)[0]
        if self.feat_type == "mfcc":
            return np.asarray(F.MFCC(sr=self.SR, **self.feat_kwargs)(x)._value)[0]
        raise ValueError(f"unknown feat_type {self.feat_type!r}")

    def __getitem__(self, idx):
        return self._features(self.waves[idx]), self.labels[idx]

    def __len__(self):
        return len(self.labels)


class TESS(_SyntheticAudio):
    """Toronto emotional speech set: 7 emotions
    (reference audio/datasets/tess.py)."""
    N_CLASSES = 7

    def __init__(self, mode="train", n_folds=1, split=1, feat_type="raw",
                 archive=None, **kwargs):
        super().__init__(mode=mode, feat_type=feat_type, seed_offset=50,
                         **kwargs)


class ESC50(_SyntheticAudio):
    """ESC-50 environmental sounds: 50 classes
    (reference audio/datasets/esc50.py)."""
    N_CLASSES = 50

    def __init__(self, mode="train", split=1, feat_type="raw",
                 archive=None, **kwargs):
        super().__init__(mode=mode, feat_type=feat_type, seed_offset=60,
                         **kwargs)
