"""Audio functional ops. Reference analog: python/paddle/audio/functional/
(functional.py: hz_to_mel/mel_to_hz/compute_fbank_matrix/power_to_db/
create_dct; window.py: get_window).

TPU-first: STFT is framing + rfft over the frame axis — one batched matmul
-shaped FFT instead of per-frame kernels.
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops._helpers import ensure_tensor, unary

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]


def hz_to_mel(freq, htk=False):
    scalar = not isinstance(freq, (Tensor, np.ndarray, list))
    f = freq._value if isinstance(freq, Tensor) else jnp.asarray(freq)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mels = jnp.where(f >= min_log_hz,
                         min_log_mel + jnp.log(f / min_log_hz) / logstep,
                         mels)
        out = mels
    return float(out) if scalar else Tensor(out)


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, (Tensor, np.ndarray, list))
    m = mel._value if isinstance(mel, Tensor) else jnp.asarray(mel)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        freqs = jnp.where(m >= min_log_mel,
                          min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                          freqs)
        out = freqs
    return float(out) if scalar else Tensor(out)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    low = hz_to_mel(float(f_min), htk=htk)
    high = hz_to_mel(float(f_max), htk=htk)
    mels = jnp.linspace(low, high, n_mels)
    return mel_to_hz(Tensor(mels), htk=htk)


def fft_frequencies(sr, n_fft):
    return Tensor(jnp.linspace(0, float(sr) / 2, 1 + n_fft // 2))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Mel filterbank [n_mels, 1 + n_fft//2]."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = fft_frequencies(sr, n_fft)._value
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)._value

    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0, name=None):
    x = ensure_tensor(spect)

    def fn(v):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, v))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec
    return unary("power_to_db", fn, x)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc]."""
    n = jnp.arange(n_mels, dtype=jnp.float64)
    k = jnp.arange(n_mfcc, dtype=jnp.float64)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct = dct * jnp.sqrt(2.0 / n_mels)
        dct = dct.at[:, 0].set(dct[:, 0] * (1.0 / math.sqrt(2)))
    else:
        dct = dct * 2.0
    return Tensor(dct.astype(dtype))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """hann/hamming/blackman/bartlett/kaiser/gaussian/... windows."""
    if isinstance(window, tuple):
        name, *params = window
    else:
        name, params = window, []
    n = win_length
    sym = not fftbins
    m = n + (0 if sym else 1)
    t = np.arange(m)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * t / (m - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * t / (m - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * t / (m - 1))
             + 0.08 * np.cos(4 * np.pi * t / (m - 1)))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * t / (m - 1) - 1.0)
    elif name == "kaiser":
        beta = params[0] if params else 12.0
        w = np.kaiser(m, beta)
    elif name == "gaussian":
        std = params[0] if params else 7.0
        w = np.exp(-0.5 * ((t - (m - 1) / 2) / std) ** 2)
    elif name in ("boxcar", "rectangular", "ones"):
        w = np.ones(m)
    else:
        raise ValueError(f"unsupported window {window!r}")
    if not sym:
        w = w[:-1]
    return Tensor(jnp.asarray(w.astype(dtype)))
