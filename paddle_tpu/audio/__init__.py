"""paddle.audio equivalent. Reference analog: python/paddle/audio/
(features, functional; backends are file-IO and out of scope on TPU hosts)."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from .features import (  # noqa: F401
    Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC,
)

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
