"""paddle.audio equivalent. Reference analog: python/paddle/audio/
(features, functional; backends are file-IO and out of scope on TPU hosts)."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from .features import (  # noqa: F401
    Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC,
)

from . import datasets  # noqa: F401
from . import backends  # noqa: F401
from .backends.backend import info, load, save  # noqa: F401

__all__ = ["functional", "features", "datasets", "backends", "load", "info",
           "save", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
