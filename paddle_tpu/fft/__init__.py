"""paddle.fft equivalent over jnp.fft. Reference analog:
python/paddle/fft.py (phi fft kernels / cuFFT)."""
from __future__ import annotations

import jax.numpy as jnp

from ..ops._helpers import ensure_tensor, unary

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft", "fftfreq",
           "rfftfreq", "fftshift", "ifftshift"]


def _wrap1(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return unary(name, lambda v: jfn(v, n=n, axis=axis, norm=norm),
                     ensure_tensor(x))
    op.__name__ = name
    return op


def _wrapn(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return unary(name, lambda v: jfn(v, s=s, axes=axes, norm=norm),
                     ensure_tensor(x))
    op.__name__ = name
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)

fft2 = _wrapn("fft2", lambda v, s, axes, norm: jnp.fft.fft2(
    v, s=s, axes=axes if axes is not None else (-2, -1), norm=norm))
ifft2 = _wrapn("ifft2", lambda v, s, axes, norm: jnp.fft.ifft2(
    v, s=s, axes=axes if axes is not None else (-2, -1), norm=norm))
rfft2 = _wrapn("rfft2", lambda v, s, axes, norm: jnp.fft.rfft2(
    v, s=s, axes=axes if axes is not None else (-2, -1), norm=norm))
irfft2 = _wrapn("irfft2", lambda v, s, axes, norm: jnp.fft.irfft2(
    v, s=s, axes=axes if axes is not None else (-2, -1), norm=norm))
fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from ..framework.core import Tensor
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from ..framework.core import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return unary("fftshift", lambda v: jnp.fft.fftshift(v, axes=axes),
                 ensure_tensor(x))


def ifftshift(x, axes=None, name=None):
    return unary("ifftshift", lambda v: jnp.fft.ifftshift(v, axes=axes),
                 ensure_tensor(x))


def _hermitian_axes(x_ndim, s, axes):
    if axes is None:
        axes = tuple(range(x_ndim)) if s is None else \
            tuple(range(x_ndim - len(s), x_ndim))
    return tuple(a % x_ndim for a in axes)


def _hfftn_impl(v, s, axes, norm):
    # hfftn = forward FFT of a Hermitian-symmetric signal (real spectrum):
    # backward-norm identity hfft(a, n) == irfft(conj(a), n) * n, extended
    # over the leading axes by plain complex FFT (reference fft_c2r kernel)
    axes = _hermitian_axes(v.ndim, s, axes)
    y = jnp.fft.irfftn(jnp.conj(v), s=s, axes=axes, norm="backward")
    n_total = 1
    for a in axes:
        n_total *= y.shape[a]
    if norm == "backward":
        return y * n_total
    if norm == "ortho":
        return y * (n_total ** 0.5)
    if norm == "forward":
        return y
    raise ValueError(f"invalid norm {norm!r}")


def _ihfftn_impl(v, s, axes, norm):
    # ihfft(a, n) == conj(rfft(a, n)) / n under backward norm
    axes = _hermitian_axes(v.ndim, s, axes)
    y = jnp.conj(jnp.fft.rfftn(v, s=s, axes=axes, norm="backward"))
    n_total = 1
    for a in axes:
        n_total *= v.shape[a] if s is None else s[list(axes).index(a)]
    if norm == "backward":
        return y / n_total
    if norm == "ortho":
        return y / (n_total ** 0.5)
    if norm == "forward":
        return y
    raise ValueError(f"invalid norm {norm!r}")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-D FFT of a signal with Hermitian symmetry (real spectrum).
    Reference: python/paddle/fft.py:778 (fft_c2r kernel)."""
    return unary("hfftn", lambda v: _hfftn_impl(v, s, axes, norm),
                 ensure_tensor(x))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of hfftn. Reference: python/paddle/fft.py:827."""
    return unary("ihfftn", lambda v: _ihfftn_impl(v, s, axes, norm),
                 ensure_tensor(x))


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D Hermitian FFT. Reference: python/paddle/fft.py:1127."""
    return hfftn(x, s=s, axes=axes, norm=norm, name=name)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm, name=name)


__all__ += ["hfft2", "hfftn", "ihfft2", "ihfftn"]
