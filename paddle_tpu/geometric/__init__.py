"""Graph ops (GNN message passing). Reference analog:
python/paddle/geometric/ (message_passing/send_recv.py, math.py,
reindex.py, sampling/neighbors.py) backed by graph_send_recv kernels.

TPU-first: message passing is expressed with jax segment reductions
(jax.ops.segment_*), which XLA lowers to sorted scatter — no CUDA atomics.
Reductions require a static out_size under jit; eager calls infer it.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops._helpers import ensure_tensor, call_op, const_input

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "reindex_graph", "sample_neighbors",
]

_SEG = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed from sum / count
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _num_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    ids = np.asarray(ids)
    return int(ids.max()) + 1 if ids.size else 0


def _segment(name, data, ids, pool, num):
    if pool == "mean":
        s = jax.ops.segment_sum(data, ids, num_segments=num)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype), ids,
                                  num_segments=num)
        cnt = jnp.maximum(cnt, 1)
        return s / cnt.reshape((-1,) + (1,) * (data.ndim - 1))
    out = _SEG[pool](data, ids, num_segments=num)
    if pool in ("max", "min"):
        # empty segments come back as +-inf; the reference zeroes them
        out = jnp.where(jnp.isfinite(out), out, 0)
    return out


def segment_sum(data, segment_ids, name=None):
    data, ids = ensure_tensor(data), const_input(segment_ids)
    num = _num_segments(ids._value, None)
    return call_op("segment_sum",
                   lambda d, iv: _segment("segment_sum", d, iv, "sum", num),
                   (data, ids))


def segment_mean(data, segment_ids, name=None):
    data, ids = ensure_tensor(data), const_input(segment_ids)
    num = _num_segments(ids._value, None)
    return call_op("segment_mean",
                   lambda d, iv: _segment("segment_mean", d, iv, "mean",
                                          num), (data, ids))


def segment_max(data, segment_ids, name=None):
    data, ids = ensure_tensor(data), const_input(segment_ids)
    num = _num_segments(ids._value, None)
    return call_op("segment_max",
                   lambda d, iv: _segment("segment_max", d, iv, "max",
                                          num), (data, ids))


def segment_min(data, segment_ids, name=None):
    data, ids = ensure_tensor(data), const_input(segment_ids)
    num = _num_segments(ids._value, None)
    return call_op("segment_min",
                   lambda d, iv: _segment("segment_min", d, iv, "min",
                                          num), (data, ids))


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and segment-reduce onto dst. Reference analog:
    geometric/message_passing/send_recv.py send_u_recv (graph_send_recv op)."""
    x = ensure_tensor(x)
    src_t, dst_t = const_input(src_index), const_input(dst_index)
    dst = dst_t._value
    num = _num_segments(dst, out_size) if out_size is not None else \
        max(_num_segments(dst, None), x.shape[0])

    def fn(v, si, di):
        return _segment("send_u_recv", v[si], di, reduce_op, num)
    return call_op("send_u_recv", fn, (x, src_t, dst_t))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y, then reduce onto
    dst. Reference analog: send_ue_recv (graph_send_ue_recv op)."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    src_t, dst_t = const_input(src_index), const_input(dst_index)
    dst = dst_t._value
    num = _num_segments(dst, out_size) if out_size is not None else \
        max(_num_segments(dst, None), x.shape[0])
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}

    def fn(v, e, si, di):
        msg = ops[message_op](v[si], e)
        return _segment("send_ue_recv", msg, di, reduce_op, num)
    return call_op("send_ue_recv", fn, (x, y, src_t, dst_t))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from src features x and dst features y.
    Reference analog: send_uv (graph_send_uv op)."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    src_t, dst_t = const_input(src_index), const_input(dst_index)
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}

    def fn(v, w, si, di):
        return ops[message_op](v[si], w[di])
    return call_op("send_uv", fn, (x, y, src_t, dst_t))


def _reindex_impl(x_np, nbrs, cnts):
    """Shared id-compaction: ids keep x first, then new neighbor ids in
    order of first appearance (the paddle reindex semantics); returns
    (reindexed neighbor lists, dst lists, out_nodes)."""
    order = {}
    for v in x_np:
        if v not in order:
            order[v] = len(order)
    for nbr in nbrs:
        for v in nbr:
            if v not in order:
                order[v] = len(order)
    remap = np.vectorize(order.__getitem__)
    re_nbrs = [remap(n) if n.size else n for n in nbrs]
    out_nodes = np.array(sorted(order, key=order.__getitem__))
    dsts = [np.repeat(remap(x_np), c) if c.size else np.array([], np.int64)
            for c in cnts]
    return re_nbrs, dsts, out_nodes


def reindex_graph(x, neighbors, count, name=None):
    """Compact global node ids to local contiguous ids. Reference analog:
    geometric/reindex.py reindex_graph. Host-side (index bookkeeping, not a
    compute-path op)."""
    x_np = np.asarray(ensure_tensor(x)._value)
    nbr = np.asarray(ensure_tensor(neighbors)._value)
    cnt = np.asarray(ensure_tensor(count)._value)
    re_nbrs, dsts, out_nodes = _reindex_impl(x_np, [nbr], [cnt])
    return (Tensor(jnp.asarray(re_nbrs[0].astype(np.int64))),
            Tensor(jnp.asarray(dsts[0].astype(np.int64))),
            Tensor(jnp.asarray(out_nodes.astype(np.int64))))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniformly sample up to sample_size neighbors per input node from a
    CSC graph. Reference analog: geometric/sampling/neighbors.py
    (graph_sample_neighbors kernel). Host-side sampling."""
    row_np = np.asarray(ensure_tensor(row)._value)
    colptr_np = np.asarray(ensure_tensor(colptr)._value)
    nodes = np.asarray(ensure_tensor(input_nodes)._value)
    eids_np = (np.asarray(ensure_tensor(eids)._value)
               if eids is not None else np.arange(len(row_np)))
    if return_eids and eids is None:
        raise ValueError("return_eids=True requires eids")
    rng = np.random.default_rng()
    out_nbr, out_cnt, out_eids = [], [], []
    for n in nodes:
        beg, end = int(colptr_np[n]), int(colptr_np[n + 1])
        take = np.arange(beg, end)
        if sample_size > 0 and len(take) > sample_size:
            take = rng.choice(take, size=sample_size, replace=False)
        out_nbr.append(row_np[take])
        out_cnt.append(len(take))
        out_eids.append(eids_np[take])
    neighbors = np.concatenate(out_nbr) if out_nbr else np.array([], np.int64)
    outs = (Tensor(jnp.asarray(neighbors.astype(np.int64))),
            Tensor(jnp.asarray(np.array(out_cnt, np.int64))))
    if return_eids:
        sampled = (np.concatenate(out_eids) if out_eids
                   else np.array([], np.int64))
        outs += (Tensor(jnp.asarray(sampled.astype(np.int64))),)
    return outs


def reindex_heter_graph(x, neighbors, count, name=None):
    """Reindex a heterogeneous graph: per-edge-type neighbor/count lists
    share ONE node-id mapping (reference: geometric/reindex.py
    reindex_heter_graph)."""
    from ..framework.core import Tensor as _T
    xs = np.asarray(ensure_tensor(x)._value)
    nbrs = [np.asarray(ensure_tensor(n)._value) for n in neighbors]
    cnts = [np.asarray(ensure_tensor(c)._value) for c in count]
    re_nbrs, dsts, out_nodes = _reindex_impl(xs, nbrs, cnts)
    cat = lambda arrs: (np.concatenate(arrs) if arrs
                        else np.array([], np.int64))
    return (_T(jnp.asarray(cat(re_nbrs).astype(np.int64))),
            _T(jnp.asarray(cat(dsts).astype(np.int64))),
            _T(jnp.asarray(out_nodes.astype(np.int64))))


__all__.append("reindex_heter_graph")
