"""paddle.static surface. Reference analog: python/paddle/static/ (Program /
Executor / InputSpec / save_inference_model).

TPU-first: a "Program" is a traced jaxpr artifact (see paddle_tpu.jit); the
Executor role is played by the XLA runtime (SURVEY.md §7 row 4), so this module
provides the API shell used by static-style user code, executing eagerly via
jit capture.
"""
from __future__ import annotations

from ..jit.api import InputSpec  # noqa: F401

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "Executor", "name_scope",
           "py_func", "save_inference_model", "load_inference_model"]


class Program:
    """Minimal Program artifact holding captured functions."""

    def __init__(self):
        self.ops = []
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self.main_program = main_program
        self.startup_program = startup_program

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class Executor:
    """Reference analog: fluid/executor.py:911 — here jit/XLA executes, so run()
    simply invokes captured callables."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        # eager-backed shell: ops already executed when built, so a run()
        # fetches current values (callables are invoked with the feed)
        results = []
        for f in (fetch_list or []):
            if callable(f):
                results.append(f(**(feed or {})))
            elif hasattr(f, "numpy"):
                results.append(f.numpy())
            else:
                results.append(f)
        return results


def py_func(func, x, out, backward_func=None):
    """Run a python callable as an op (reference: fluid/layers/py_func_op).
    Eager-first: call `func` on the input tensors now; `out` (a Tensor or
    list prototype, per the reference API) receives the result values."""
    from ..framework.core import Tensor
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    res = res if isinstance(res, (list, tuple)) else [res]
    outs = out if isinstance(out, (list, tuple)) else [out]
    import jax.numpy as jnp
    for o, r in zip(outs, res):
        o._value = r._value if isinstance(r, Tensor) else jnp.asarray(r)
    return out


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         layer=None, input_spec=None, **kwargs):
    """TPU-native: the inference artifact is jax.export StableHLO. Pass the
    Layer (and optionally input_spec; defaults to feed_vars when those are
    InputSpecs) — program+executor arguments exist for API parity."""
    from ..jit.api import save as jit_save
    if layer is None and hasattr(fetch_vars, "state_dict"):
        layer = fetch_vars
    if layer is None:
        raise ValueError(
            "save_inference_model needs the Layer: "
            "save_inference_model(path, feed_vars=[InputSpec...], "
            "fetch_vars=layer) or layer=...")
    spec = input_spec
    if spec is None and feed_vars and all(
            hasattr(v, "shape") for v in feed_vars):
        spec = list(feed_vars)
    jit_save(layer, path_prefix + ".pdmodel", input_spec=spec)
    # this artifact's sole purpose is the compiled forward — surface export
    # failure here, not at predictor creation on the deployment host
    from ..framework.io import load as fload
    payload = fload(path_prefix + ".pdmodel")
    if "stablehlo" not in payload:
        raise RuntimeError(
            "save_inference_model: StableHLO export failed: "
            + str(payload.get("stablehlo_error", "no input_spec given")))


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit.api import load as jit_load
    return jit_load(path_prefix)
