"""paddle.static surface. Reference analog: python/paddle/static/ (Program /
Executor / InputSpec / save_inference_model).

TPU-first: a "Program" is a traced jaxpr artifact (see paddle_tpu.jit); the
Executor role is played by the XLA runtime (SURVEY.md §7 row 4), so this module
provides the API shell used by static-style user code, executing eagerly via
jit capture.
"""
from __future__ import annotations

import contextlib

import numpy as np

from ..jit.api import InputSpec  # noqa: F401

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "Executor",
           "name_scope", "py_func", "save_inference_model",
           "load_inference_model", "data", "Variable", "append_backward",
           "gradients", "create_global_var", "create_parameter",
           "global_scope", "scope_guard", "BuildStrategy",
           "ExecutionStrategy", "CompiledProgram", "ParallelExecutor",
           "Print", "WeightNormParamAttr", "ExponentialMovingAverage",
           "accuracy", "auc", "ctr_metric_bundle", "exponential_decay",
           "device_guard", "cpu_places", "cuda_places", "xpu_places",
           "npu_places", "mlu_places", "save", "load", "serialize_program",
           "serialize_persistables", "save_to_file", "deserialize_program",
           "deserialize_persistables", "load_from_file",
           "normalize_program", "load_program_state", "set_program_state",
           "ipu_shard_guard", "IpuCompiledProgram", "IpuStrategy",
           "set_ipu_shard"]


class Program:
    """Minimal Program artifact holding captured functions."""

    def __init__(self):
        self.ops = []
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self.main_program = main_program
        self.startup_program = startup_program

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _FetchTarget:
    """Opaque fetch handle (reference analog: the fetch Variables
    load_inference_model returns from static/io.py)."""

    def __init__(self, index, name):
        self.index = index
        self.name = name

    def __repr__(self):
        return f"FetchTarget({self.name})"


class _InferenceProgram(Program):
    """A loaded inference artifact with feed/fetch rewiring: Executor.run
    feeds by NAME in the saved order and fetches by target (reference:
    static/io.py load_inference_model -> [program, feed_names, fetches],
    run through fluid/executor.py feed/fetch rewiring)."""

    def __init__(self, translated, feed_names):
        super().__init__()
        self._translated = translated
        self.feed_names = list(feed_names)

    def _run(self, feed, fetch_list=None):
        import numpy as np
        feed = feed or {}
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise KeyError(
                f"feed is missing {missing}; expected names "
                f"{self.feed_names}")
        args = [feed[n] for n in self.feed_names]
        out = self._translated(*args)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        outs = [o.numpy() if hasattr(o, "numpy") else np.asarray(o)
                for o in outs]
        if fetch_list:
            picked = []
            for f in fetch_list:
                idx = f.index if isinstance(f, _FetchTarget) else int(f)
                if idx >= len(outs):
                    raise IndexError(
                        f"fetch target {f!r} out of range: program "
                        f"produced {len(outs)} outputs")
                picked.append(outs[idx])
            return picked
        return outs


class Executor:
    """Reference analog: fluid/executor.py:911 — here jit/XLA executes, so run()
    simply invokes captured callables."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if isinstance(program, _InferenceProgram):
            return program._run(feed, fetch_list)
        if callable(program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        # eager-backed shell: ops already executed when built, so a run()
        # fetches current values (callables are invoked with the feed)
        results = []
        for f in (fetch_list or []):
            if callable(f):
                results.append(f(**(feed or {})))
            elif hasattr(f, "numpy"):
                results.append(f.numpy())
            else:
                results.append(f)
        return results


def py_func(func, x, out, backward_func=None):
    """Run a python callable as an op (reference: fluid/layers/py_func_op).
    Eager-first: call `func` on the input tensors now; `out` (a Tensor or
    list prototype, per the reference API) receives the result values."""
    from ..framework.core import Tensor
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    res = res if isinstance(res, (list, tuple)) else [res]
    outs = out if isinstance(out, (list, tuple)) else [out]
    import jax.numpy as jnp
    for o, r in zip(outs, res):
        o._value = r._value if isinstance(r, Tensor) else jnp.asarray(r)
    return out


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         layer=None, input_spec=None, **kwargs):
    """TPU-native: the inference artifact is jax.export StableHLO. Pass the
    Layer (and optionally input_spec; defaults to feed_vars when those are
    InputSpecs) — program+executor arguments exist for API parity."""
    from ..jit.api import save as jit_save
    if layer is None and hasattr(fetch_vars, "state_dict"):
        layer = fetch_vars
    if layer is None:
        raise ValueError(
            "save_inference_model needs the Layer: "
            "save_inference_model(path, feed_vars=[InputSpec...], "
            "fetch_vars=layer) or layer=...")
    spec = input_spec
    if spec is None and feed_vars and all(
            hasattr(v, "shape") for v in feed_vars):
        spec = list(feed_vars)
    jit_save(layer, path_prefix + ".pdmodel", input_spec=spec)
    # this artifact's sole purpose is the compiled forward — surface export
    # failure here, not at predictor creation on the deployment host
    from ..framework.io import load as fload
    payload = fload(path_prefix + ".pdmodel")
    if "stablehlo" not in payload:
        raise RuntimeError(
            "save_inference_model: StableHLO export failed: "
            + str(payload.get("stablehlo_error", "no input_spec given")))
    # feed/fetch metadata sidecar so load_inference_model can rewire by
    # name (reference: static/io.py records feed_target_names /
    # fetch_targets in the serialized program)
    import json as _json
    feed_names = []
    for i, v in enumerate(spec or []):
        feed_names.append(getattr(v, "name", None) or f"feed_{i}")
    with open(path_prefix + ".pdmodel.meta", "w") as f:
        _json.dump({"feed_names": feed_names}, f)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns [program, feed_target_names, fetch_targets] (the reference
    static/io.py contract). Run via Executor.run(program, feed={name: np},
    fetch_list=fetch_targets)."""
    import os
    import json as _json
    from ..jit.api import load as jit_load
    # accept both the bare prefix and a full ".pdmodel" path
    if path_prefix.endswith(".pdmodel"):
        path_prefix = path_prefix[:-len(".pdmodel")]
    translated = jit_load(path_prefix + ".pdmodel"
                          if os.path.exists(path_prefix + ".pdmodel")
                          else path_prefix)
    meta_path = path_prefix + ".pdmodel.meta"
    feed_names = []
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            feed_names = _json.load(f).get("feed_names", [])
    program = _InferenceProgram(translated, feed_names)
    # one fetch target per model output (out_avals minus the updated-buffer
    # outputs the export appends), mirroring the reference's one target per
    # fetch var
    n_out = 1
    exported = getattr(translated, "_exported", None)
    if exported is not None:
        n_buf = translated._payload.get("n_buffer_outputs", 0)
        n_out = max(1, len(exported.out_avals) - n_buf)
    fetch_targets = [_FetchTarget(i, f"fetch_{i}") for i in range(n_out)]
    return [program, feed_names, fetch_targets]


# --------------------------------------------------------------------------
# static-graph surface (reference: python/paddle/static/{input,io,nn}.py +
# fluid shells). Eager-first: "variables" are Tensors, the graph is the
# traced jaxpr, so most entries execute directly; the legacy executor/
# build-strategy machinery is an API-parity shell (XLA owns scheduling).
# --------------------------------------------------------------------------

def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed slot (reference: static/input.py:26). Returns a named
    InputSpec consumed by save_inference_model / to_static input_spec."""
    return InputSpec(shape, dtype or "float32", name=name)


def _tensor_cls():
    from ..framework.core import Tensor
    return Tensor


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Reference: fluid/backward.py append_backward — builds the grad ops.
    Eager: runs backward() and returns the (param, grad) pairs."""
    loss.backward()
    if parameter_list is not None:
        params = parameter_list
    else:
        from ..framework.core import Parameter
        params = [t for t in _live_parameters() if not t.stop_gradient]
    return [(p, p.grad) for p in params if getattr(p, "grad", None)
            is not None]


def _live_parameters():
    """Parameters touched by the current tape (best effort for the
    parameter_list=None legacy path)."""
    import gc
    from ..framework.core import Parameter
    return [o for o in gc.get_objects() if isinstance(o, Parameter)]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d(targets)/d(inputs) (reference: fluid/backward.py gradients)."""
    from ..framework.autograd import grad as _grad
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)


class Variable:          # reference: static Variable ≙ eager Tensor here
    def __new__(cls, *args, **kwargs):
        return _tensor_cls()(*args, **kwargs)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    import jax.numpy as jnp
    t = _tensor_cls()(jnp.full(tuple(shape), value, dtype), stop_gradient=True)
    if name:
        t.name = name
        global_scope().vars[name] = t
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..framework.core import Parameter
    import jax.numpy as jnp
    from ..nn import initializer as I
    if default_initializer is None:
        default_initializer = I.Constant(0.0) if is_bias \
            else I.XavierUniform()       # seeded by paddle.seed
    if isinstance(default_initializer, I.Initializer):
        val = default_initializer(tuple(shape), dtype)
    else:                                # callable applied to a prototype
        from ..framework.core import Tensor as _T
        proto = _T(jnp.zeros(tuple(shape), dtype))
        default_initializer(proto)
        val = proto._value
    p = Parameter(val)
    if name:
        p.name = name
    return p


# ----------------------------------------------------------------- scopes
class Scope:
    """Name -> Tensor registry (reference: framework/scope.h). The XLA
    runtime owns real variable lifetime; this serves the find_var/get
    legacy API."""

    def __init__(self):
        self.vars = {}

    def var(self, name):
        from ..framework.core import Tensor
        import jax.numpy as jnp
        if name not in self.vars:
            self.vars[name] = Tensor(jnp.zeros((), jnp.float32),
                                     stop_gradient=True)
        return self.vars[name]

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


# ------------------------------------------------- legacy executor shells
class BuildStrategy:
    """Graph-build knobs (reference: details/build_strategy.h). XLA fuses
    and schedules; the attributes are accepted and recorded."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_all_optimizer_ops = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.reduce_strategy = None
        self.gradient_scale_strategy = None
        self.build_cinn_pass = False

    def __setattr__(self, k, v):        # accept any knob, like the pybind
        object.__setattr__(self, k, v)  # struct does


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.use_thread_barrier = False

    def __setattr__(self, k, v):
        object.__setattr__(self, k, v)


class CompiledProgram:
    """Reference: compiler.py CompiledProgram — wraps a program with build
    strategies. XLA compiles on first run, so this records and passes
    through."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        if build_strategy is not None:
            self._build_strategy = build_strategy
        return self

    def __call__(self, *args, **kwargs):
        return self._program(*args, **kwargs) if callable(self._program) \
            else self._program


class ParallelExecutor:
    """Legacy multi-device executor shell (reference:
    framework/parallel_executor.cc). The SPMD mesh replaces it; runs the
    program via the standard Executor."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, scope=None,
                 share_vars_from=None):
        self._program = main_program
        self._exe = Executor()

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed,
                             fetch_list=fetch_list)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference: fluid/layers/control_flow.py Print):
    eager-prints and passes the tensor through."""
    vals = np.asarray(input._value).reshape(-1)[:summarize]
    head = (message + " ") if message else ""
    name = getattr(input, "name", "") if print_tensor_name else ""
    print(f"{head}{name} shape={tuple(input._value.shape)} "
          f"dtype={input._value.dtype} values={vals}")
    return input


class WeightNormParamAttr:
    """Reference: fluid/param_attr.py WeightNormParamAttr — marks a param
    for weight normalization along `dim` (consumed by nn.utils.weight_norm
    here)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference:
    fluid/optimizer.py ExponentialMovingAverage): update() after each step,
    apply()/restore() swap the shadow weights in and out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._thres_steps = thres_steps
        self._shadow = {}
        self._backup = {}
        self._params = None
        self._step = 0

    def _targets(self):
        if self._params is None:
            self._params = [p for p in _live_parameters()
                            if not p.stop_gradient]
        return self._params

    def register(self, parameters=None):
        self._params = list(parameters) if parameters is not None else None
        for p in self._targets():
            self._shadow[id(p)] = p._value
        return self

    def update(self):
        import jax.numpy as jnp
        self._step += 1
        # reference semantics: the (1+t)/(10+t) warmup ramp applies only
        # when thres_steps is given; otherwise decay is constant
        d = self._decay if self._thres_steps is None else \
            min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._targets():
            prev = self._shadow.get(id(p), p._value)
            self._shadow[id(p)] = (d * prev.astype(jnp.float32)
                                   + (1 - d) * p._value.astype(jnp.float32))

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._value for p in self._targets()}
        for p in self._targets():
            if id(p) in self._shadow:
                p._value = self._shadow[id(p)].astype(p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._targets():
            if id(p) in self._backup:
                p._value = self._backup[id(p)]
        self._backup = {}


# ------------------------------------------------------------- metrics
def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference: static/nn/metric.py accuracy)."""
    import jax.numpy as jnp
    logits = input._value
    lab = label._value.reshape(-1)
    topk = jnp.argsort(-logits, axis=-1)[:, :k]
    hit = (topk == lab[:, None]).any(axis=-1)
    return _tensor_cls()(jnp.mean(hit.astype(jnp.float32)),
                         stop_gradient=True)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Area under the ROC curve of P(class 1) (reference:
    static/nn/metric.py auc)."""
    import jax.numpy as jnp
    probs = np.asarray(input._value)
    pos_score = probs[:, 1] if probs.ndim == 2 and probs.shape[1] > 1 \
        else probs.reshape(-1)
    lab = np.asarray(label._value).reshape(-1)
    order = np.argsort(-pos_score)
    lab = lab[order]
    tps = np.cumsum(lab)
    fps = np.cumsum(1 - lab)
    tpr = tps / max(tps[-1], 1)
    fpr = fps / max(fps[-1], 1)
    val = float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
        else float(np.trapz(tpr, fpr))
    return _tensor_cls()(jnp.asarray(val, jnp.float32), stop_gradient=True)


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR eval bundle: (auc, mae, rmse, predicted_ctr, actual_ctr)
    (reference: static/nn/metric.py ctr_metric_bundle)."""
    import jax.numpy as jnp
    T = _tensor_cls()
    probs = np.asarray(input._value).reshape(-1)
    lab = np.asarray(label._value).reshape(-1).astype(np.float32)
    a = auc(input, label)
    mae = float(np.abs(probs - lab).mean())
    rmse = float(np.sqrt(((probs - lab) ** 2).mean()))
    return (a, T(jnp.asarray(mae, jnp.float32), stop_gradient=True),
            T(jnp.asarray(rmse, jnp.float32), stop_gradient=True),
            T(jnp.asarray(float(probs.mean()), jnp.float32),
              stop_gradient=True),
            T(jnp.asarray(float(lab.mean()), jnp.float32),
              stop_gradient=True))


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """Reference: fluid/layers/learning_rate_scheduler.py —
    lr * decay_rate^(step/decay_steps), with staircase flooring the
    exponent (flat plateaus of decay_steps)."""
    from ..optimizer.lr import LRScheduler

    class _ExpDecayBySteps(LRScheduler):
        def get_lr(self):
            t = max(self.last_epoch, 0) / float(decay_steps)
            if staircase:
                t = float(int(t))
            return self.base_lr * (decay_rate ** t)

    return _ExpDecayBySteps(learning_rate=learning_rate)


# ------------------------------------------------------------- places
@contextlib.contextmanager
def device_guard(device=None):
    """Reference: static/device_guard — pins op placement. XLA/GSPMD place
    ops; accepted for parity."""
    yield


def cpu_places(device_count=None):
    import jax
    cpus = jax.devices("cpu")
    return cpus[:device_count] if device_count else cpus


def cuda_places(device_ids=None):
    return []          # no CUDA in the TPU build


def xpu_places(device_ids=None):
    return []


def npu_places(device_ids=None):
    return []


def mlu_places(device_ids=None):
    return []


# ------------------------------------------------- program serialization
def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """Program bytes (reference: static/io.py serialize_program). The
    TPU-native program is the jax.export StableHLO blob."""
    import pickle
    from ..jit.api import save as jit_save
    import tempfile, os as _os
    layer = program if program is not None else fetch_vars
    with tempfile.TemporaryDirectory() as td:
        path = _os.path.join(td, "prog.pdmodel")
        jit_save(layer, path, input_spec=list(feed_vars) if feed_vars
                 else None)
        from ..framework.io import load as fload
        payload = fload(path)
    # program only, no persistables — but NON-persistable buffers are part
    # of the program machinery, not the weights: keep their slot values so
    # set_state can re-arm the artifact
    keys = payload.get("export_state_keys") or []
    export_state = payload.pop("export_state", None) or []
    payload["export_state_aux"] = {
        i: v for i, (k, v) in enumerate(zip(keys, export_state))
        if k is None}
    payload.pop("state_dict", None)
    return pickle.dumps(payload)


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    """Weight bytes (reference: static/io.py serialize_persistables)."""
    import pickle
    layer = program if program is not None else fetch_vars
    state = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    return pickle.dumps(state)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    import pickle
    from ..jit.api import TranslatedLayer
    return TranslatedLayer(pickle.loads(data))


def deserialize_persistables(program, data, executor=None):
    import pickle
    return pickle.loads(data)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference: static/io.py normalize_program prunes to the inference
    graph; export already captures exactly the forward, so identity."""
    return program


def save(program, model_path, protocol=4, **configs):
    """paddle.static.save: persist a Layer-backed 'program' state
    (reference: static/io.py save -> .pdparams/.pdopt)."""
    from ..framework import io as _io
    target = getattr(program, "_program", program)
    _io.save(target.state_dict() if hasattr(target, "state_dict")
             else target, model_path + ".pdparams")


def load(program, model_path, executor=None, var_list=None):
    from ..framework import io as _io
    state = _io.load(model_path + ".pdparams")
    target = getattr(program, "_program", program)
    if hasattr(target, "set_state_dict"):
        target.set_state_dict(state)
    return state


def load_program_state(model_path, var_list=None):
    from ..framework import io as _io
    state = _io.load(model_path + ".pdparams")
    return {k: np.asarray(v._value) if hasattr(v, "_value") else
            np.asarray(v) for k, v in state.items()}


def set_program_state(program, state_dict):
    target = getattr(program, "_program", program)
    if hasattr(target, "set_state_dict"):
        target.set_state_dict(state_dict)


# ------------------------------------------------------------- IPU shims
def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError(
        "IPU support is vendor-specific and not part of the TPU build; "
        "use the mesh axes (paddle_tpu.distributed) for placement")


def set_ipu_shard(layer, index=-1, stage=-1):
    raise NotImplementedError(
        "IPU support is vendor-specific and not part of the TPU build")


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError(
            "IPU support is vendor-specific and not part of the TPU build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "IPU support is vendor-specific and not part of the TPU build")
