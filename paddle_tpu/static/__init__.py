"""paddle.static surface. Reference analog: python/paddle/static/ (Program /
Executor / InputSpec / save_inference_model).

TPU-first: a "Program" is a traced jaxpr artifact (see paddle_tpu.jit); the
Executor role is played by the XLA runtime (SURVEY.md §7 row 4), so this module
provides the API shell used by static-style user code, executing eagerly via
jit capture.
"""
from __future__ import annotations

from ..jit.api import InputSpec  # noqa: F401

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "Executor", "name_scope",
           "py_func", "save_inference_model", "load_inference_model"]


class Program:
    """Minimal Program artifact holding captured functions."""

    def __init__(self):
        self.ops = []
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy
        return copy.copy(self)


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self.main_program = main_program
        self.startup_program = startup_program

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _FetchTarget:
    """Opaque fetch handle (reference analog: the fetch Variables
    load_inference_model returns from static/io.py)."""

    def __init__(self, index, name):
        self.index = index
        self.name = name

    def __repr__(self):
        return f"FetchTarget({self.name})"


class _InferenceProgram(Program):
    """A loaded inference artifact with feed/fetch rewiring: Executor.run
    feeds by NAME in the saved order and fetches by target (reference:
    static/io.py load_inference_model -> [program, feed_names, fetches],
    run through fluid/executor.py feed/fetch rewiring)."""

    def __init__(self, translated, feed_names):
        super().__init__()
        self._translated = translated
        self.feed_names = list(feed_names)

    def _run(self, feed, fetch_list=None):
        import numpy as np
        feed = feed or {}
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise KeyError(
                f"feed is missing {missing}; expected names "
                f"{self.feed_names}")
        args = [feed[n] for n in self.feed_names]
        out = self._translated(*args)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        outs = [o.numpy() if hasattr(o, "numpy") else np.asarray(o)
                for o in outs]
        if fetch_list:
            picked = []
            for f in fetch_list:
                idx = f.index if isinstance(f, _FetchTarget) else int(f)
                if idx >= len(outs):
                    raise IndexError(
                        f"fetch target {f!r} out of range: program "
                        f"produced {len(outs)} outputs")
                picked.append(outs[idx])
            return picked
        return outs


class Executor:
    """Reference analog: fluid/executor.py:911 — here jit/XLA executes, so run()
    simply invokes captured callables."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if isinstance(program, _InferenceProgram):
            return program._run(feed, fetch_list)
        if callable(program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        # eager-backed shell: ops already executed when built, so a run()
        # fetches current values (callables are invoked with the feed)
        results = []
        for f in (fetch_list or []):
            if callable(f):
                results.append(f(**(feed or {})))
            elif hasattr(f, "numpy"):
                results.append(f.numpy())
            else:
                results.append(f)
        return results


def py_func(func, x, out, backward_func=None):
    """Run a python callable as an op (reference: fluid/layers/py_func_op).
    Eager-first: call `func` on the input tensors now; `out` (a Tensor or
    list prototype, per the reference API) receives the result values."""
    from ..framework.core import Tensor
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    res = res if isinstance(res, (list, tuple)) else [res]
    outs = out if isinstance(out, (list, tuple)) else [out]
    import jax.numpy as jnp
    for o, r in zip(outs, res):
        o._value = r._value if isinstance(r, Tensor) else jnp.asarray(r)
    return out


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         layer=None, input_spec=None, **kwargs):
    """TPU-native: the inference artifact is jax.export StableHLO. Pass the
    Layer (and optionally input_spec; defaults to feed_vars when those are
    InputSpecs) — program+executor arguments exist for API parity."""
    from ..jit.api import save as jit_save
    if layer is None and hasattr(fetch_vars, "state_dict"):
        layer = fetch_vars
    if layer is None:
        raise ValueError(
            "save_inference_model needs the Layer: "
            "save_inference_model(path, feed_vars=[InputSpec...], "
            "fetch_vars=layer) or layer=...")
    spec = input_spec
    if spec is None and feed_vars and all(
            hasattr(v, "shape") for v in feed_vars):
        spec = list(feed_vars)
    jit_save(layer, path_prefix + ".pdmodel", input_spec=spec)
    # this artifact's sole purpose is the compiled forward — surface export
    # failure here, not at predictor creation on the deployment host
    from ..framework.io import load as fload
    payload = fload(path_prefix + ".pdmodel")
    if "stablehlo" not in payload:
        raise RuntimeError(
            "save_inference_model: StableHLO export failed: "
            + str(payload.get("stablehlo_error", "no input_spec given")))
    # feed/fetch metadata sidecar so load_inference_model can rewire by
    # name (reference: static/io.py records feed_target_names /
    # fetch_targets in the serialized program)
    import json as _json
    feed_names = []
    for i, v in enumerate(spec or []):
        feed_names.append(getattr(v, "name", None) or f"feed_{i}")
    with open(path_prefix + ".pdmodel.meta", "w") as f:
        _json.dump({"feed_names": feed_names}, f)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns [program, feed_target_names, fetch_targets] (the reference
    static/io.py contract). Run via Executor.run(program, feed={name: np},
    fetch_list=fetch_targets)."""
    import os
    import json as _json
    from ..jit.api import load as jit_load
    # accept both the bare prefix and a full ".pdmodel" path
    if path_prefix.endswith(".pdmodel"):
        path_prefix = path_prefix[:-len(".pdmodel")]
    translated = jit_load(path_prefix + ".pdmodel"
                          if os.path.exists(path_prefix + ".pdmodel")
                          else path_prefix)
    meta_path = path_prefix + ".pdmodel.meta"
    feed_names = []
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            feed_names = _json.load(f).get("feed_names", [])
    program = _InferenceProgram(translated, feed_names)
    # one fetch target per model output (out_avals minus the updated-buffer
    # outputs the export appends), mirroring the reference's one target per
    # fetch var
    n_out = 1
    exported = getattr(translated, "_exported", None)
    if exported is not None:
        n_buf = translated._payload.get("n_buffer_outputs", 0)
        n_out = max(1, len(exported.out_avals) - n_buf)
    fetch_targets = [_FetchTarget(i, f"fetch_{i}") for i in range(n_out)]
    return [program, feed_names, fetch_targets]
