"""paddle.static.nn — static-graph layer functions.

Reference analog: python/paddle/static/nn/ (fc, conv2d, batch_norm,
embedding, cond, while_loop, switch_case over the fluid layers/controlflow
ops).

TPU-first: "static" building here means trace-compatible functions — layer
params are created once per call-site name in a process-wide registry (the
Program's parameter scope analog) and the control-flow ops map onto
lax.cond/lax.while_loop, which keeps them compilable under jit instead of
becoming Python-side branches.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops._helpers import ensure_tensor
from ..utils import unique_name

__all__ = ["fc", "embedding", "conv2d", "batch_norm", "cond", "while_loop",
           "switch_case", "case"]

# parameter scope: call-site name -> Layer (the startup-program analog)
_LAYERS = {}


def _get_layer(name, factory):
    if name is None:
        raise ValueError("static.nn layers need name= (the parameter scope "
                         "key; the reference derives it from unique_name)")
    if name not in _LAYERS:
        _LAYERS[name] = factory()
    return _LAYERS[name]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from ..nn.layer.common import Linear
    from ..ops import manipulation as manip
    x = ensure_tensor(x)
    name = name or unique_name.generate("fc")
    lead = x.shape[:num_flatten_dims]
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= s
    layer = _get_layer(name, lambda: Linear(
        in_features, size, weight_attr=weight_attr, bias_attr=bias_attr))
    flat = manip.reshape(x, list(lead) + [in_features])
    out = layer(flat)
    if activation is not None:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    from ..nn.layer.common import Embedding
    name = name or unique_name.generate("embedding")
    layer = _get_layer(name, lambda: Embedding(
        size[0], size[1], padding_idx=padding_idx, weight_attr=param_attr))
    return layer(ensure_tensor(input))


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    from ..nn.layer.conv import Conv2D
    x = ensure_tensor(input)
    name = name or unique_name.generate("conv2d")
    in_channels = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    layer = _get_layer(name, lambda: Conv2D(
        in_channels, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format))
    out = layer(x)
    if act is not None:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    from ..nn.layer.norm import BatchNorm2D, BatchNorm1D
    x = ensure_tensor(input)
    name = name or unique_name.generate("batch_norm")
    ch = x.shape[1] if data_layout == "NCHW" else x.shape[-1]
    cls = BatchNorm2D if len(x.shape) == 4 else BatchNorm1D
    layer = _get_layer(name, lambda: cls(ch, momentum=momentum,
                                         epsilon=epsilon))
    if is_test:
        layer.eval()
    out = layer(x)
    if act is not None:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out


# ------------------------------------------------------------ control flow

def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _wrap_out(v):
    if isinstance(v, (list, tuple)):
        return type(v)(_wrap_out(e) for e in v)
    return Tensor(v) if not isinstance(v, Tensor) else v


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Reference: fluid/layers/control_flow cond (conditional_block ops).
    Lowers to lax.cond so both branches stay inside one compiled graph."""
    p = _unwrap(ensure_tensor(pred))
    p = jnp.reshape(p, ()).astype(bool)

    def t_branch(_):
        out = true_fn()
        return jax.tree_util.tree_map(_unwrap, out)

    def f_branch(_):
        out = false_fn()
        return jax.tree_util.tree_map(_unwrap, out)

    out = jax.lax.cond(p, t_branch, f_branch, operand=None)
    return _wrap_out(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Reference: fluid while op. Lowers to lax.while_loop (compilable
    data-dependent trip count)."""
    init = [_unwrap(ensure_tensor(v)) for v in loop_vars]

    def c(vals):
        out = cond_fn(*[Tensor(v, stop_gradient=True) for v in vals])
        return jnp.reshape(_unwrap(out), ()).astype(bool)

    def b(vals):
        out = body_fn(*[Tensor(v, stop_gradient=True) for v in vals])
        out = out if isinstance(out, (list, tuple)) else [out]
        return [_unwrap(ensure_tensor(o)) for o in out]

    final = jax.lax.while_loop(c, b, init)
    return [_wrap_out(v) for v in final]


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Reference: fluid switch_case. Lowers to lax.switch."""
    idx = jnp.reshape(_unwrap(ensure_tensor(branch_index)), ()).astype(
        jnp.int32)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        # map arbitrary branch keys onto dense switch indices
        lut = jnp.full((max(keys) + 2,), len(fns), jnp.int32)
        for pos, k in enumerate(keys):
            lut = lut.at[k].set(pos)
        idx = lut[jnp.clip(idx, 0, max(keys) + 1)]
    else:
        fns = list(branch_fns)
        idx = jnp.clip(idx, 0, len(fns))
    if default is not None:
        fns = fns + [default]
    else:
        fns = fns + [fns[-1]]

    wrapped = [lambda _, f=f: jax.tree_util.tree_map(_unwrap, f())
               for f in fns]
    out = jax.lax.switch(jnp.minimum(idx, len(fns) - 1), wrapped, None)
    return _wrap_out(out)


def case(pred_fn_pairs, default=None, name=None):
    """Reference: fluid case. First true predicate wins."""
    preds = [jnp.reshape(_unwrap(ensure_tensor(p)), ()).astype(jnp.int32)
             for p, _ in pred_fn_pairs]
    fns = [f for _, f in pred_fn_pairs]
    stacked = jnp.stack(preds)
    first = jnp.argmax(stacked)
    any_true = jnp.any(stacked > 0)
    idx = jnp.where(any_true, first, len(fns))
    if default is None:
        default = fns[-1]
    wrapped = [lambda _, f=f: jax.tree_util.tree_map(_unwrap, f())
               for f in fns + [default]]
    out = jax.lax.switch(idx.astype(jnp.int32), wrapped, None)
    return _wrap_out(out)


_SPARSE_TABLES = {}


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None, name=None):
    """PS-backed sparse embedding lookup (reference:
    fluid/contrib/layers/nn.py:1072 sparse_embedding over the PS sparse
    table; pairs with paddle.distributed entry rules —
    distributed/entry_attr.py).

    TPU-native: rows live in a host-side ps.SparseTable materialized on
    first touch and gated by `entry` admission (ProbabilityEntry /
    CountFilterEntry / ShowClickEntry); the lookup result is a dense
    Tensor. Training updates flow through the PS push path
    (distributed.ps / DownpourSGD trainer), not autograd — exactly the
    reference's split between dense program and sparse table."""
    import numpy as np
    from ..distributed.ps import SparseTable
    from ..framework.core import Tensor

    x = ensure_tensor(input)
    key = name or getattr(param_attr, "name", None)
    if not key:
        # an auto-generated key would be fresh EVERY call: the table (and
        # every PS push into it) would be lost between steps
        raise ValueError(
            "sparse_embedding needs a stable identity: pass name=... or "
            "param_attr=ParamAttr(name=...) so lookups across steps hit "
            "the same PS table")
    table = _SPARSE_TABLES.get(key)
    if table is None:
        table = SparseTable(key, int(size[1]), entry=entry)
        _SPARSE_TABLES[key] = table
    elif table.dim != int(size[1]):
        raise ValueError(
            f"sparse_embedding {key!r} already exists with dim "
            f"{table.dim}; got size={list(size)}")
    ids = np.asarray(x._value).reshape(-1).astype(np.int64)
    if padding_idx is not None:
        rows = np.zeros((ids.size, int(size[1])), np.float32)
        mask = ids != padding_idx
        if mask.any():
            rows[mask] = table.pull(ids[mask])
    else:
        rows = table.pull(ids)
    out_shape = tuple(x._value.shape) + (int(size[1]),)
    return Tensor(jnp.asarray(rows.reshape(out_shape), dtype))


__all__.append("sparse_embedding")


from .nn_ext import *  # noqa: F401,F403,E402
from .nn_ext import __all__ as _ext_all  # noqa: E402
__all__ += [n for n in _ext_all if n not in __all__]
__all__.append("py_func")
from . import py_func  # noqa: F401,E402
