"""paddle.static.sparsity — ASP (2:4 structured sparsity) static surface.

Reference analog: python/paddle/static/sparsity/__init__.py re-exporting
incubate/asp. The implementations live in paddle_tpu.incubate.asp."""
from ...incubate.asp import (  # noqa: F401
    calculate_density, decorate, prune_model, set_excluded_layers,
    reset_excluded_layers,
)

_SUPPORTED_LAYERS = {}


def add_supported_layer(layer, pruning_func=None):
    """Register a custom layer type for ASP pruning (reference
    asp/supported_layer_list.py add_supported_layer)."""
    name = layer if isinstance(layer, str) else getattr(
        layer, "__name__", str(layer))
    _SUPPORTED_LAYERS[name] = pruning_func
    return name


__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers",
           "add_supported_layer"]
