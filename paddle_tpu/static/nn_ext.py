"""static.nn builders beyond the core set — layer delegates, normalizers,
and the sequence_* family.

Reference analog: python/paddle/static/nn/__init__.py (41 exports over
fluid layers). TPU-first representation notes:

  - LoD does not exist: a "sequence batch" is a PADDED dense tensor
    [B, T, ...] plus an optional `lengths` argument ([B] int). Every
    sequence_* op takes that form; ops whose reference output is ragged
    (sequence_unpad) return the flattened valid rows.
  - parameters live in the same call-site layer scope as fc/embedding
    (static/nn.py:_get_layer — the startup-program analog), so repeated
    calls with one `name` reuse weights.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..ops._helpers import ensure_tensor
from ..ops.dispatch import call_op
from ..utils import unique_name

__all__ = [
    "bilinear_tensor_product", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "crf_decoding", "data_norm", "deform_conv2d",
    "group_norm", "instance_norm", "layer_norm", "multi_box_head", "nce",
    "prelu", "row_conv", "spectral_norm", "sequence_conv",
    "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad",
    "sequence_unpad", "sequence_reshape", "sequence_scatter",
    "sequence_enumerate", "sequence_reverse", "StaticRNN",
]


def _scope(name, factory):
    from .nn import _get_layer
    return _get_layer(name, factory)


def _v(x):
    return ensure_tensor(x)._value


def _t(v):
    return Tensor(v, stop_gradient=True)


# ------------------------------------------------------- layer delegates

def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from ..nn.layer.common import Bilinear
    xt, yt = ensure_tensor(x), ensure_tensor(y)
    layer = _scope(name, lambda: Bilinear(
        xt.shape[-1], yt.shape[-1], size, weight_attr=param_attr,
        bias_attr=bias_attr))
    out = layer(xt, yt)
    if act == "relu":
        import paddle_tpu.nn.functional as F
        out = F.relu(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from ..nn.layer.conv import Conv2DTranspose
    x = ensure_tensor(input)
    layer = _scope(name, lambda: Conv2DTranspose(
        x.shape[1], num_filters, filter_size or 3, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format))
    return layer(x)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    from ..nn.layer.conv import Conv3D
    x = ensure_tensor(input)
    layer = _scope(name, lambda: Conv3D(
        x.shape[1], num_filters, filter_size, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format))
    return layer(x)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from ..nn.layer.conv import Conv3DTranspose
    x = ensure_tensor(input)
    layer = _scope(name, lambda: Conv3DTranspose(
        x.shape[1], num_filters, filter_size or 3, stride=stride,
        padding=padding, dilation=dilation, groups=groups,
        weight_attr=param_attr, bias_attr=bias_attr,
        data_format=data_format))
    return layer(x)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn.initializer_util import materialize_parameter
    from ..vision.ops import deform_conv2d as _dc
    x = ensure_tensor(input)
    k = filter_size if isinstance(filter_size, (tuple, list)) else \
        (filter_size, filter_size)

    class _DeformParams:
        def __init__(self):
            self.weight = materialize_parameter(
                [num_filters, x.shape[1] // groups, k[0], k[1]], param_attr,
                "float32")
            self.bias = materialize_parameter(
                [num_filters], bias_attr, "float32", is_bias=True) \
                if bias_attr is not False else None

    p = _scope(name, _DeformParams)
    return _dc(x, offset, p.weight, bias=p.bias, stride=stride,
               padding=padding, dilation=dilation,
               deformable_groups=deformable_groups, groups=groups,
               mask=mask)


def group_norm(input, groups, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ..nn.layer.norm import GroupNorm
    x = ensure_tensor(input)
    layer = _scope(name, lambda: GroupNorm(
        groups, x.shape[1], epsilon=epsilon, weight_attr=param_attr,
        bias_attr=bias_attr))
    return layer(x)


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn.layer.norm import InstanceNorm2D
    x = ensure_tensor(input)
    layer = _scope(name, lambda: InstanceNorm2D(
        x.shape[1], epsilon=epsilon, weight_attr=param_attr,
        bias_attr=bias_attr))
    return layer(x)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn.layer.norm import LayerNorm
    x = ensure_tensor(input)
    norm_shape = list(x.shape[begin_norm_axis:])
    layer = _scope(name, lambda: LayerNorm(
        norm_shape, epsilon=epsilon,
        weight_attr=param_attr if scale else False,
        bias_attr=bias_attr if shift else False))
    return layer(x)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    from ..nn.layer.activation import PReLU
    xt = ensure_tensor(x)
    num = 1 if mode == "all" else (
        xt.shape[1] if mode == "channel" else int(np.prod(xt.shape[1:])))
    layer = _scope(name, lambda: PReLU(num_parameters=num,
                                       weight_attr=param_attr,
                                       data_format=data_format))
    return layer(xt)


def crf_decoding(input, param_attr=None, label=None, length=None,
                 name=None):
    """Viterbi decode against a learned transition parameter (reference:
    fluid crf_decoding over linear_chain_crf's transition). input:
    [B, T, N] emissions."""
    from ..nn.initializer_util import materialize_parameter
    from ..text import viterbi_decode
    x = ensure_tensor(input)
    n_tags = x.shape[-1]

    class _Transition:
        def __init__(self):
            self.weight = materialize_parameter(
                [n_tags + 2, n_tags], param_attr, "float32")

    trans = _scope(name, _Transition)
    lens = length if length is not None else _t(
        jnp.full((x.shape[0],), x.shape[1], jnp.int64))
    # the learned table's first two rows are start/stop in the reference;
    # the square body drives the pairwise transitions
    body = Tensor(trans.weight._value[2:, :])
    _, path = viterbi_decode(x, body, lens, include_bos_eos_tag=False)
    return path


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_0=0.9999999, enable_scale_and_shift=False):
    """Normalization by ACCUMULATED batch statistics (reference: fluid
    data_norm op — PS-CTR feature normalization keeping batch_size/
    batch_sum/batch_square_sum accumulators, no learned scale)."""
    from ..nn.initializer_util import materialize_parameter
    x = ensure_tensor(input)
    d = x.shape[-1]

    from ..nn import initializer as I

    class _Stats:
        def __init__(self):
            self.batch_size = materialize_parameter(
                [d], None, "float32", default_initializer=I.Constant(1e4))
            self.batch_sum = materialize_parameter(
                [d], None, "float32", default_initializer=I.Constant(0.0))
            self.batch_square_sum = materialize_parameter(
                [d], None, "float32", default_initializer=I.Constant(1e4))
            for p in (self.batch_size, self.batch_sum,
                      self.batch_square_sum):
                p.stop_gradient = True

    s = _scope(name, _Stats)
    mean = s.batch_sum._value / s.batch_size._value
    scale = jnp.sqrt(s.batch_size._value / s.batch_square_sum._value)
    out_t = call_op("data_norm",
                    lambda v: (v - mean) * scale, (x,))
    # accumulate this batch into the stats (the op's saved outputs)
    n = float(np.prod(x.shape[:-1]))
    s.batch_size._value = s.batch_size._value + n
    s.batch_sum._value = s.batch_sum._value + x._value.reshape(-1, d).sum(0)
    s.batch_square_sum._value = s.batch_square_sum._value + \
        (x._value.reshape(-1, d) ** 2).sum(0)
    return out_t


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference: fluid nce op).
    input [B, D], label [B, 1] or [B]; returns [B, 1] per-example loss."""
    from ..nn.initializer_util import materialize_parameter
    x = ensure_tensor(input)
    y = ensure_tensor(label)
    d = x.shape[-1]
    k = int(num_neg_samples or 10)

    class _NCE:
        def __init__(self):
            self.weight = materialize_parameter(
                [num_total_classes, d], param_attr, "float32")
            self.bias = materialize_parameter(
                [num_total_classes], bias_attr, "float32", is_bias=True) \
                if bias_attr is not False else None

    p = _scope(name, _NCE)
    yv = y._value.reshape(-1).astype(jnp.int32)
    rng = np.random.default_rng(seed)
    neg = jnp.asarray(
        rng.integers(0, num_total_classes, (x.shape[0], k)), jnp.int32)

    def fn(xv, wv, *rest):
        pos_logit = jnp.einsum("bd,bd->b", xv, wv[yv])
        neg_logit = jnp.einsum("bd,bkd->bk", xv, wv[neg])
        if rest:
            pos_logit = pos_logit + rest[0][yv]
            neg_logit = neg_logit + rest[0][neg]
        loss = -jax.nn.log_sigmoid(pos_logit) \
            - jax.nn.log_sigmoid(-neg_logit).sum(-1)
        return loss[:, None]
    ins = (x, p.weight) + ((p.bias,) if p.bias is not None else ())
    return call_op("nce", fn, ins)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Lookahead row convolution (reference: fluid row_conv op —
    DeepSpeech2's streaming-friendly temporal filter). input [B, T, D]."""
    from ..nn.initializer_util import materialize_parameter
    x = ensure_tensor(input)
    d = x.shape[-1]
    w = future_context_size + 1

    class _RowConv:
        def __init__(self):
            self.weight = materialize_parameter([w, d], param_attr,
                                                "float32")

    p = _scope(name, _RowConv)

    def fn(v, wv):
        pad = jnp.pad(v, ((0, 0), (0, future_context_size), (0, 0)))
        return sum(pad[:, i:i + v.shape[1], :] * wv[i] for i in range(w))
    return call_op("row_conv", fn, (x, p.weight))


_SN_STATE = {}


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Spectrally-normalized view of `weight` (reference: fluid
    spectral_norm op — power iteration on the unrolled matrix)."""
    wt = ensure_tensor(weight)
    nd = wt._value.ndim
    perm = [dim] + [i for i in range(nd) if i != dim]
    # persistent power-iteration state (the reference op's weight_u var):
    # keyed by name (or the weight's identity) so sigma REFINES across
    # steps instead of restarting from the same random vector
    key = name or id(weight)
    u0 = _SN_STATE.get(key)
    if u0 is None:
        u0 = jax.random.normal(jax.random.PRNGKey(0),
                               (wt._value.shape[dim],))
    mat_now = jnp.transpose(wt._value, perm).reshape(
        wt._value.shape[dim], -1)
    u_now = u0
    for _ in range(max(int(power_iters), 1)):
        v_now = mat_now.T @ u_now
        v_now = v_now / (jnp.linalg.norm(v_now) + eps)
        u_now = mat_now @ v_now
        u_now = u_now / (jnp.linalg.norm(u_now) + eps)
    _SN_STATE[key] = u_now

    def fn(w):
        mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
        v = mat.T @ u_now
        v = v / (jnp.linalg.norm(v) + eps)
        sigma = u_now @ mat @ v
        return w / sigma
    return call_op("spectral_norm", fn, (wt,))


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference: fluid multi_box_head): per feature
    map, conv heads predict box offsets and class scores against generated
    prior boxes. Returns (mbox_locs, mbox_confs, boxes, variances)."""
    from ..nn.layer.conv import Conv2D
    if min_sizes is None:
        # reference ratio schedule
        num_layer = len(inputs)
        min_ratio, max_ratio = min_ratio or 20, max_ratio or 90
        step = int((max_ratio - min_ratio) / max(num_layer - 2, 1))
        min_sizes, max_sizes = [base_size * 0.1], [base_size * 0.2]
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
    locs, confs, priors, vars_ = [], [], [], []
    img_h, img_w = ensure_tensor(image).shape[2:4]
    for i, feat in enumerate(inputs):
        f = ensure_tensor(feat)
        ar = aspect_ratios[i] if i < len(aspect_ratios) else [1.0]
        # build the per-cell size list FIRST: the conv heads' channel
        # count must equal the number of priors actually generated
        sizes = []
        mn = min_sizes[i] / base_size
        sizes.append((mn, mn))
        if i < len(max_sizes) and max_sizes[i]:
            mx = (mn * max_sizes[i] / base_size) ** 0.5
            sizes.append((mx, mx))
        for a in ar:
            if a == 1.0:
                continue
            sizes.append((mn * a ** 0.5, mn / a ** 0.5))
            if flip:
                sizes.append((mn / a ** 0.5, mn * a ** 0.5))
        n_prior = len(sizes)
        loc_conv = _scope(f"{name or 'mbox'}_loc_{i}", lambda f=f, n=n_prior:
                          Conv2D(f.shape[1], n * 4, kernel_size,
                                 stride=stride, padding=pad))
        conf_conv = _scope(f"{name or 'mbox'}_conf_{i}",
                           lambda f=f, n=n_prior:
                           Conv2D(f.shape[1], n * num_classes, kernel_size,
                                  stride=stride, padding=pad))
        loc = loc_conv(f)._value
        conf = conf_conv(f)._value
        b, _, fh, fw = loc.shape
        locs.append(loc.transpose(0, 2, 3, 1).reshape(b, -1, 4))
        confs.append(conf.transpose(0, 2, 3, 1)
                     .reshape(b, -1, num_classes))
        # prior boxes: centered grid, one box per size per cell
        ys, xs = jnp.meshgrid(
            (jnp.arange(fh) + offset) / fh,
            (jnp.arange(fw) + offset) / fw, indexing="ij")
        for (sw, sh) in sizes:
            box = jnp.stack([xs - sw / 2, ys - sh / 2,
                             xs + sw / 2, ys + sh / 2], -1).reshape(-1, 4)
            if clip:
                box = jnp.clip(box, 0.0, 1.0)
            priors.append(box)
            vars_.append(jnp.broadcast_to(
                jnp.asarray(variance, jnp.float32), box.shape))
    mbox_locs = jnp.concatenate(locs, 1)
    mbox_confs = jnp.concatenate(confs, 1)
    boxes = jnp.concatenate(priors, 0)
    variances = jnp.concatenate(vars_, 0)
    return _t(mbox_locs), _t(mbox_confs), _t(boxes), _t(variances)


# ------------------------------------------------------- sequence family

def _len_mask(v, lengths):
    if lengths is None:
        return None
    lv = ensure_tensor(lengths)._value.reshape(-1)
    return jnp.arange(v.shape[1])[None, :] < lv[:, None]


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Temporal conv over padded [B, T, D] sequences (reference: fluid
    sequence_conv over LoD rows)."""
    from ..nn.initializer_util import materialize_parameter
    x = ensure_tensor(input)
    d = x.shape[-1]

    class _SeqConv:
        def __init__(self):
            self.weight = materialize_parameter(
                [filter_size * d, num_filters], param_attr, "float32")
            self.bias = materialize_parameter(
                [num_filters], bias_attr, "float32", is_bias=True) \
                if bias_attr is not False else None

    p = _scope(name, _SeqConv)
    start = padding_start if padding_start is not None else \
        -((filter_size - 1) // 2)
    lo = max(-start, 0)
    hi = max(filter_size - 1 + start, 0)

    def fn(v, w, *rest):
        pad = jnp.pad(v, ((0, 0), (lo, hi), (0, 0)))
        windows = jnp.concatenate(
            [pad[:, i:i + v.shape[1], :] for i in range(filter_size)], -1)
        out = windows @ w
        if rest:
            out = out + rest[0]
        return out

    ins = (x, p.weight) + ((p.bias,) if p.bias is not None else ())
    return call_op("sequence_conv", fn, ins)


def sequence_softmax(input, use_cudnn=False, name=None, lengths=None):
    x = ensure_tensor(input)
    mask = _len_mask(x._value, lengths)

    def fn(v):
        vv = v
        if mask is not None:
            vv = jnp.where(mask[..., None] if v.ndim == 3 else mask,
                           vv, -1e30)
        out = jax.nn.softmax(vv, axis=1)
        if mask is not None:
            out = jnp.where(mask[..., None] if v.ndim == 3 else mask,
                            out, 0.0)
        return out
    return call_op("sequence_softmax", fn, (x,))


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  lengths=None):
    x = ensure_tensor(input)
    mask = _len_mask(x._value, lengths)
    lv = None if lengths is None else \
        ensure_tensor(lengths)._value.reshape(-1).astype(jnp.int32)
    pt = pool_type.lower()
    if pt not in ("sum", "average", "sqrt", "max", "first", "last"):
        raise ValueError(f"unknown pool_type {pool_type!r}")

    def fn(v):
        m3 = None if mask is None else mask[..., None]
        if pt in ("sum", "average", "sqrt"):
            vv = v if m3 is None else jnp.where(m3, v, 0.0)
            s = vv.sum(1)
            if pt == "sum":
                return s
            n = jnp.maximum(mask.sum(1), 1)[..., None] \
                if mask is not None else float(v.shape[1])
            return s / (jnp.sqrt(n) if pt == "sqrt" else n)
        if pt == "max":
            vv = v if m3 is None else jnp.where(m3, v, -jnp.inf)
            return vv.max(1)
        if pt == "first":
            return v[:, 0]
        if lv is None:
            return v[:, -1]
        return jnp.take_along_axis(
            v, jnp.maximum(lv - 1, 0)[:, None, None], 1)[:, 0]
    return call_op("sequence_pool", fn, (x,))


def sequence_first_step(input, lengths=None):
    return sequence_pool(input, "first", lengths=lengths)


def sequence_last_step(input, lengths=None):
    return sequence_pool(input, "last", lengths=lengths)


def sequence_concat(input, name=None):
    ts = [ensure_tensor(t) for t in input]

    def fn(*vals):
        return jnp.concatenate(vals, axis=1)
    return call_op("sequence_concat", fn, tuple(ts))


def sequence_slice(input, offset, length, name=None):
    x = ensure_tensor(input)
    off = ensure_tensor(offset)._value.reshape(-1).astype(jnp.int32)
    ln = ensure_tensor(length)._value.reshape(-1).astype(jnp.int32)
    out_len = int(ln[0])
    if not bool(jnp.all(ln == out_len)):
        raise ValueError(
            "sequence_slice on the padded representation needs equal "
            "lengths per batch row (ragged output has no dense tensor)")
    def fn(v):
        return jnp.stack([
            jax.lax.dynamic_slice_in_dim(v[b], off[b], out_len, 0)
            for b in range(v.shape[0])])
    return call_op("sequence_slice", fn, (x,))


def sequence_expand(x, y, ref_level=-1, name=None):
    """Tile each of x's rows to y's time length (reference expands rows by
    y's LoD; padded analog: repeat along a new/existing time dim)."""
    xt = ensure_tensor(x)
    t = ensure_tensor(y).shape[1]

    if len(xt.shape) == 3 and t % xt.shape[1] != 0:
        raise ValueError(
            f"sequence_expand: y's length {t} must be a multiple of x's "
            f"length {xt.shape[1]} in the padded representation (the "
            "reference repeats whole sub-sequences per LoD)")

    def fn(xv):
        if xv.ndim == 2:
            return jnp.repeat(xv[:, None, :], t, axis=1)
        return jnp.tile(xv, (1, t // xv.shape[1], 1))
    return call_op("sequence_expand", fn, (xt,))


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value, maxlen=None, name=None, lengths=None):
    """Pad/trim the time dim to maxlen; returns (padded, lengths)
    (reference returns Length as second output)."""
    xt = ensure_tensor(x)
    pv = float(ensure_tensor(pad_value)._value) \
        if not isinstance(pad_value, (int, float)) else float(pad_value)
    t = xt.shape[1]
    target = int(maxlen or t)

    def fn(v):
        if target > t:
            return jnp.pad(
                v, ((0, 0), (0, target - t)) + ((0, 0),) * (v.ndim - 2),
                constant_values=pv)
        return v[:, :target]

    padded = call_op("sequence_pad", fn, (xt,))
    if lengths is None:
        lens = jnp.full((xt.shape[0],), min(t, target), jnp.int64)
    else:
        lens = jnp.minimum(ensure_tensor(lengths)._value.reshape(-1), target)
    return padded, _t(lens)


def sequence_unpad(x, length, name=None):
    """Drop padding: returns the concatenated valid rows [sum(len), ...]
    (the reference's LoD output flattened — the only dense form)."""
    xt = ensure_tensor(x)
    lv = np.asarray(ensure_tensor(length)._value).reshape(-1).astype(int)
    rows = [np.asarray(xt._value[b, :lv[b]]) for b in range(xt.shape[0])]
    return _t(jnp.asarray(np.concatenate(rows, 0)))


def sequence_reshape(input, new_dim):
    x = ensure_tensor(input)
    v = x._value
    total = v.shape[1] * v.shape[2]
    if total % new_dim:
        raise ValueError(f"cannot reshape feature {v.shape[1]}x{v.shape[2]} "
                         f"to rows of {new_dim}")
    return call_op("sequence_reshape", lambda vv: vv.reshape(
        vv.shape[0], total // new_dim, new_dim), (x,))


def sequence_scatter(input, index, updates, name=None):
    x = ensure_tensor(input)
    idx = ensure_tensor(index)._value.astype(jnp.int32)
    upd = ensure_tensor(updates)
    b = jnp.arange(x.shape[0])[:, None]

    def fn(v, u):
        return v.at[b, idx].add(u)
    return call_op("sequence_scatter", fn, (x, upd))


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    x = ensure_tensor(input)
    v = x._value
    pad = jnp.pad(v, ((0, 0), (0, win_size - 1)),
                  constant_values=int(pad_value))
    wins = jnp.stack([pad[:, i:i + v.shape[1]] for i in range(win_size)],
                     -1)
    return _t(wins)


def sequence_reverse(x, name=None, lengths=None):
    xt = ensure_tensor(x)
    lv = None if lengths is None else \
        ensure_tensor(lengths)._value.reshape(-1).astype(jnp.int32)

    def fn(v):
        if lv is None:
            return v[:, ::-1]
        idx = jnp.arange(v.shape[1])[None, :]
        src = jnp.where(idx < lv[:, None], lv[:, None] - 1 - idx, idx)
        return jnp.take_along_axis(
            v, src[..., None] if v.ndim == 3 else src, 1)
    return call_op("sequence_reverse", fn, (xt,))


class StaticRNN:
    """Step-builder RNN (reference: fluid StaticRNN — step_input/memory/
    update_memory/output record ops into a block re-executed per step).

    TPU-first: the user's step block runs eagerly ONCE (on the t=0 slice),
    wiring the autograd tape from the step cursors to the outputs; __call__
    replays that tape as a PURE function (framework.autograd.replay_pure —
    the same machinery as double-grad) and drives it with ONE lax.scan over
    the time dim. Parameters touched inside the block are discovered from
    the tape and threaded as explicit scan inputs, so gradients flow to
    them exactly as in the reference."""

    def __init__(self, name=None):
        self._inputs = []
        self._memories = []
        self._outputs = []

    def step(self):
        class _Ctx:
            def __enter__(ctx):
                return ctx

            def __exit__(ctx, *exc):
                return False
        return _Ctx()

    def step_input(self, x):
        xt = ensure_tensor(x)
        cursor = Tensor(xt._value[:, 0], stop_gradient=False)
        self._inputs.append({"value": xt, "cursor": cursor})
        return cursor

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is None:
            if batch_ref is None:
                raise ValueError("memory() needs init or batch_ref")
            b = ensure_tensor(batch_ref).shape[0]
            init = Tensor(jnp.full((b,) + tuple(shape or ()),
                                   float(init_value), jnp.float32),
                          stop_gradient=True)
        init = ensure_tensor(init)
        cursor = Tensor(init._value, stop_gradient=False)
        self._memories.append({"init": init, "cursor": cursor,
                               "update": None})
        return cursor

    def update_memory(self, mem, new):
        for slot in self._memories:
            if slot["cursor"] is mem:
                slot["update"] = ensure_tensor(new)
                return
        raise ValueError("update_memory: unknown memory tensor")

    def output(self, *outputs):
        self._outputs.extend(ensure_tensor(o) for o in outputs)

    def _leaf_params(self, roots, exclude_ids):
        """Parameters the step block touched: AccumulationNode leaves of
        the recorded graph, minus the step cursors."""
        from ..framework.autograd import AccumulationNode
        seen, leaves = set(), []
        stack = [t._grad_node for t in roots if t._grad_node is not None]
        while stack:
            node = stack.pop()
            if node is None or id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node, AccumulationNode):
                t = node.tensor_ref()
                if t is not None and id(t) not in exclude_ids:
                    leaves.append(t)
                continue
            for edge in getattr(node, "edges", ()):
                if edge is not None:
                    stack.append(edge[0])
        return leaves

    def __call__(self):
        """Scan the recorded step over the time dim; returns the stacked
        outputs [B, T, ...] (one Tensor per output slot)."""
        from ..framework.autograd import replay_pure
        from ..ops.dispatch import call_op_multi
        if not self._inputs:
            raise ValueError("StaticRNN needs at least one step_input")
        if not self._outputs:
            raise ValueError("StaticRNN needs at least one output()")
        cursors = [s["cursor"] for s in self._inputs]
        mems = [s["cursor"] for s in self._memories]
        updates = [s["update"] if s["update"] is not None else s["cursor"]
                   for s in self._memories]
        roots = list(self._outputs) + list(updates)
        exclude = {id(c) for c in cursors + mems}
        params = self._leaf_params(roots, exclude)
        F = replay_pure(roots, cursors + mems + params)
        n_out, n_in, n_mem = len(self._outputs), len(cursors), len(mems)

        def scan_fn(*vals):
            seqs = vals[:n_in]
            mem0 = vals[n_in:n_in + n_mem]
            pvals = vals[n_in + n_mem:]

            def body(carry, xs):
                res = F(*xs, *carry, *pvals)
                outs = res[:n_out]
                new_mems = res[n_out:]
                return tuple(new_mems), tuple(outs)

            xs_tm = tuple(jnp.swapaxes(s, 0, 1) for s in seqs)  # [T, B, ..]
            _, ys = jax.lax.scan(body, tuple(mem0), xs_tm)
            return tuple(jnp.swapaxes(y, 0, 1) for y in ys)

        full_inputs = [s["value"] for s in self._inputs] + \
            [s["init"] for s in self._memories] + params
        outs = call_op_multi("static_rnn", scan_fn, tuple(full_inputs),
                     num_outputs=n_out)
        return outs[0] if len(outs) == 1 else list(outs)
